"""Perf doctor: machine-readable diagnosis + CI gate over a traced step.

Turns a captured trace artifact (profiling/capture.py) into the same
finding/baseline machinery graft-lint uses, so a perf regression gates a
pipeline exactly like a collective-census drift does::

    python -m deepspeed_tpu.profiling.doctor --trace bench_artifacts/trace_seq2048.json.gz
    python -m deepspeed_tpu.profiling.doctor --trace T --write-baseline doctor_baseline.json
    python -m deepspeed_tpu.profiling.doctor --trace T --baseline doctor_baseline.json
    python -m deepspeed_tpu.profiling.doctor --corpus exposed-collective-trace

Rules:

  * ``stall-regression``      — a bucket's fraction of step time grew past
                                the baseline by more than the tolerance
  * ``exposed-collective-measured`` — measured exposed-comm time exceeds
                                the allowed fraction of the step (the
                                default gate; fires with no baseline)
  * ``modeled-measured-divergence`` — measured exposed-comm ms diverges
                                from the static OverlapAudit's modeled
                                ``exposed_comm_ms`` by > 25% (warning: one
                                of the two models is lying)
  * ``offload-overlap``       — the layer-streamed step left too much of
                                its storage IO exposed (``--offload-decomp``)
  * ``serving-phase-stall``   — a NON-fetch serving round phase dominates
                                round wall time (``--serving-decomp``;
                                ISSUE 18 — fetch-bound is the healthy
                                "device is the bottleneck" state)
  * ``tracing-sync-leak``     — request tracing performed device syncs or
                                exceeds the < 1% overhead budget

Exit status: non-zero when any error finding survives — the CI gate.
"""

import argparse
import gzip
import json
import os
import sys
from typing import Any, Dict, List, Optional

from deepspeed_tpu.analysis.report import Finding, Report
from deepspeed_tpu.profiling import trace_analysis
from deepspeed_tpu.profiling.trace_analysis import (classify_bounds,
                                                    join_census,
                                                    stall_ranking, stall_top2)

# measured exposed collective time above this fraction of the step is an
# error even without a baseline — wire latency the scheduler is not hiding
MAX_EXPOSED_COMM_FRACTION = 0.15
# modeled (OverlapAudit) vs measured exposed-comm divergence warning bar
DIVERGENCE_TOLERANCE = 0.25
# baseline gating: a bucket must grow BOTH 20% relative and 2 points of
# step fraction before stall-regression fires (absolute floor keeps noise
# on tiny buckets from gating)
REGRESSION_REL = 0.20
REGRESSION_ABS = 0.02
# offload pipeline gate: the measured share of the streamed step's storage
# IO the executor hid under compute (bench: offload_overlap_fraction).
# Below this the capacity rung is paying serialized wire/host time the
# three-way read || update || write schedule exists to hide.
OFFLOAD_MIN_OVERLAP = 0.8


def diagnose(trace: Any, hlo_text: str = "", *,
             cost: Optional[Dict[str, Any]] = None,
             steps: int = 1,
             modeled_exposed_comm_ms: Optional[float] = None,
             accel=None) -> Dict[str, Any]:
    """Full attribution + roofline + census join + top-2 stalls for one
    traced step. Pure host work — no jax import on the happy path."""
    if accel is None:
        from deepspeed_tpu.accelerator import get_accelerator
        accel = get_accelerator()
    scope_map = (trace_analysis.parse_hlo_scopes(hlo_text)
                 if hlo_text else None)
    attr = trace_analysis.attribute(trace, scope_map, steps=steps)
    bounds = classify_bounds(
        attr, cost,
        peak_flops=accel.peak_flops_per_device("bf16"),
        hbm_bytes_per_sec=accel.hbm_bytes_per_sec())
    out = {
        "step_span_ms": round(attr.step_span_ms, 4),
        "device_busy_ms": round(attr.device_busy_ms, 4),
        "fwd_ms": round(attr.fwd_ms, 4),
        "bwd_ms": round(attr.bwd_ms, 4),
        "buckets": attr.buckets,
        "bounds": bounds,
        "by_scope_ms": {k: round(v, 4) for k, v in sorted(
            attr.by_scope_ms.items(), key=lambda kv: -kv[1])},
        "exposed_comm_ms": round(attr.exposed_comm_ms, 4),
        "stalls": stall_ranking(attr, bounds),
        "stall_top2": stall_top2(attr, bounds),
        "joined_ops": attr.joined_ops,
        "total_ops": attr.total_ops,
    }
    if cost and cost.get("census"):
        out["collective_join"] = join_census(attr, cost["census"])
    if modeled_exposed_comm_ms is not None:
        out["modeled_exposed_comm_ms"] = round(modeled_exposed_comm_ms, 4)
        hi = max(attr.exposed_comm_ms, modeled_exposed_comm_ms)
        div = (abs(attr.exposed_comm_ms - modeled_exposed_comm_ms) / hi
               if hi > 0 else 0.0)
        out["exposed_comm_divergence"] = round(div, 4)
    return out


def gate(diag: Dict[str, Any], *,
         baseline: Optional[Dict[str, Any]] = None,
         max_exposed_fraction: float = MAX_EXPOSED_COMM_FRACTION,
         program: str = "traced_step") -> Report:
    """Apply the doctor's gating rules to a diagnosis. Returns a Report in
    the graft-lint mold: ``report.ok`` is the exit status, findings carry
    rule/ident for baseline suppression."""
    report = Report(meta={"tool": "perf-doctor", "program": program,
                          "step_span_ms": diag.get("step_span_ms")})
    span = diag.get("step_span_ms") or 0.0
    exposed = diag.get("exposed_comm_ms") or 0.0
    if span > 0 and exposed / span > max_exposed_fraction:
        report.extend([Finding(
            rule="exposed-collective-measured",
            message=(f"measured exposed collective time {exposed:.3f} ms is "
                     f"{exposed / span:.1%} of the {span:.3f} ms step "
                     f"(budget {max_exposed_fraction:.0%}) — the scheduler "
                     "is not hiding this wire time under compute"),
            program=program, ident="exposed",
            data={"exposed_comm_ms": exposed, "step_span_ms": span})])
    div = diag.get("exposed_comm_divergence")
    if div is not None and div > DIVERGENCE_TOLERANCE:
        report.extend([Finding(
            rule="modeled-measured-divergence", severity="warning",
            message=(f"measured exposed-comm {exposed:.3f} ms vs modeled "
                     f"{diag.get('modeled_exposed_comm_ms'):.3f} ms diverge "
                     f"{div:.0%} (> {DIVERGENCE_TOLERANCE:.0%}) — the "
                     "overlap model or the interconnect pricing is off"),
            program=program, ident="divergence",
            data={"divergence": div})])
    if baseline:
        base_buckets = baseline.get("buckets", {})
        for name, stat in diag.get("buckets", {}).items():
            base = base_buckets.get(name)
            if base is None:
                continue
            cur_f, base_f = stat["fraction"], base.get("fraction", 0.0)
            if (cur_f - base_f > REGRESSION_ABS
                    and cur_f > base_f * (1 + REGRESSION_REL)):
                report.extend([Finding(
                    rule="stall-regression",
                    message=(f"bucket '{name}' grew to {cur_f:.1%} of the "
                             f"step (baseline {base_f:.1%}) — attribution "
                             "regression"),
                    program=program, ident=name,
                    data={"fraction": cur_f, "baseline": base_f})])
    return report


def diagnose_offload(decomp: Dict[str, Any],
                     step_ms: Optional[float] = None) -> Dict[str, Any]:
    """Host-stall attribution for the offload phases of a layer-streamed
    step, from the measured decomposition
    (``InfinityExecutor.measure_decomposition``) plus a measured step time.

    Attribution: compute = L x (layer fwd+bwd) + L x (chunk Adam) + the
    embed/CE-head top; io = 2L param-chunk fetches + L opt-chunk
    round-trips; everything the step spent beyond compute is EXPOSED io/
    host stall (clamped to the io budget), and
    ``offload_overlap_fraction = 1 - exposed/io`` prices how much of the
    storage traffic the pipeline actually hid under compute."""
    compute = (float(decomp.get("offload_compute_ms", 0.0))
               + float(decomp.get("offload_update_sweep_ms", 0.0))
               + float(decomp.get("offload_top_ms", 0.0)))
    io = float(decomp.get("offload_io_ms")
               or decomp.get("offload_dma_ms") or 0.0)
    out: Dict[str, Any] = {
        "offload_compute_total_ms": round(compute, 2),
        "offload_io_ms": round(io, 2),
        "offload_pipeline": decomp.get("offload_pipeline"),
    }
    if step_ms is None:
        step_ms = decomp.get("offload_step_ms")
    if step_ms:
        exposed = max(0.0, min(float(step_ms) - compute, io))
        out["offload_step_ms"] = round(float(step_ms), 2)
        out["offload_exposed_io_ms"] = round(exposed, 2)
        out["offload_overlap_fraction"] = (round(1.0 - exposed / io, 4)
                                           if io > 0 else 1.0)
        # which phase dominates the step — the "turn this knob" signal
        phases = {"layer-compute": float(decomp.get("offload_compute_ms",
                                                    0.0)),
                  "host-adam": float(decomp.get("offload_update_sweep_ms",
                                                0.0)),
                  "top-compute": float(decomp.get("offload_top_ms", 0.0)),
                  "exposed-io-stall": exposed}
        out["offload_dominant_phase"] = max(phases, key=phases.get)
    elif "offload_overlap_fraction" in decomp:
        out["offload_overlap_fraction"] = decomp["offload_overlap_fraction"]
    return out


def gate_offload(diag: Dict[str, Any], *,
                 min_overlap: float = OFFLOAD_MIN_OVERLAP,
                 program: str = "offload_step") -> Report:
    """The ``offload-overlap`` rule: the streamed step left more than
    (1 - min_overlap) of its storage IO exposed — the executor is running
    fetch -> compute -> host-Adam -> write-back serially instead of the
    three-way pipeline. Report in the graft-lint mold (exit status = CI
    gate); the corpus twin is ``offload-serial-pipeline``."""
    report = Report(meta={"tool": "perf-doctor", "program": program,
                          "offload": diag})
    frac = diag.get("offload_overlap_fraction")
    if frac is None:
        # fail CLOSED: a gate that cannot price the overlap (no
        # offload_step_ms / offload_overlap_fraction in the input) must
        # not certify the pipeline it never measured
        report.extend([Finding(
            rule="offload-overlap",
            message="offload overlap cannot be priced: the decomposition "
                    "carries no offload_overlap_fraction and no "
                    "offload_step_ms (pass the measured step time "
                    "alongside the measure_decomposition fields)",
            program=program, ident="unpriced", data=dict(diag))])
        return report
    if frac < min_overlap:
        exposed = diag.get("offload_exposed_io_ms", 0.0)
        io = diag.get("offload_io_ms", 0.0)
        report.extend([Finding(
            rule="offload-overlap",
            message=(f"offload pipeline hid only {frac:.0%} of the streamed "
                     f"step's {io:.1f} ms storage IO under compute (budget "
                     f"{min_overlap:.0%}; {exposed:.1f} ms exposed host "
                     f"stall, dominant phase "
                     f"{diag.get('offload_dominant_phase', 'unknown')}) — "
                     "check offload_param/offload_optimizer "
                     "pipeline_read/pipeline_write and the aio "
                     "read_queue_depth/write_queue_depth"),
            program=program, ident="offload-overlap",
            data={"stall": "host-io", **diag})])
    return report


def offload_fields(diag: Dict[str, Any]) -> Dict[str, Any]:
    """The bench-JSON fields for the offload attribution."""
    keys = ("offload_overlap_fraction", "offload_exposed_io_ms",
            "offload_io_ms", "offload_dominant_phase")
    return {k: diag[k] for k in keys if k in diag}


# --------------------------------------------------------------------------
# serving doctor (ISSUE 18)
# --------------------------------------------------------------------------

# a NON-fetch phase of the serving round loop above this fraction of round
# wall time is a stall the knob table names; fetch is exempt — the round's
# ONE sync legitimately waits on the device, so fetch-dominant means "the
# accelerator is the bottleneck", which is the healthy steady state
SERVING_MAX_PHASE_FRACTION = 0.5
# request tracing must stay under this much added round time (and ZERO
# device syncs) — _serving_bench asserts the same bar as
# serve_trace_overhead_pct
TRACE_MAX_OVERHEAD_PCT = 1.0

# phase -> which resource the round is actually bound on
SERVING_BOUND = {
    "schedule": "host-scheduling-bound",
    "commit": "host-scheduling-bound",
    "prefill_dispatch": "dispatch-bound",
    "decode_dispatch": "dispatch-bound",
    "fetch": "fetch-bound",
    "housekeeping": "paging-bound",
}
# the "turn this knob" message per dominant phase
SERVING_KNOBS = {
    "schedule": "raise decode_quantum (fewer scheduling boundaries per "
                "token) or cap max_seqs — the Python scheduler is the "
                "bottleneck",
    "commit": "raise decode_quantum or thin the per-token host "
              "bookkeeping — round-boundary commit work dominates",
    "prefill_dispatch": "set/raise prefill_token_budget so long prompts "
                        "chunk instead of monopolizing rounds, and check "
                        "prompt_bucket for compile churn",
    "decode_dispatch": "fewer, larger steps: raise decode_quantum, or "
                       "hunt per-step recompiles (decode_backend/bucket "
                       "drift)",
    "fetch": "healthy: the device is the bottleneck — scale the mesh or "
             "shrink the model, not the host loop",
    "housekeeping": "adapter paging / CoW fork traffic dominates: more "
                    "adapter_slots (or adapter-affinity routing) so hot "
                    "adapters stay resident instead of re-paging",
}


def diagnose_serving(decomp: Dict[str, Any]) -> Dict[str, Any]:
    """Round-phase attribution for the serving loop, from
    ``ServingEngine.phase_decomposition()`` output: per-phase fractions of
    round wall time, the dominant phase and its bound
    (host-scheduling-bound / dispatch-bound / fetch-bound / paging-bound),
    the top-2 phases for the bench, per-token round cost, and the tracing
    evidence (device-sync self-report + measured overhead) passed through
    for ``gate_serving``."""
    phases = {
        "schedule": float(decomp.get("serve_schedule_ms", 0.0)),
        "housekeeping": float(decomp.get("serve_housekeeping_ms", 0.0)),
        "prefill_dispatch": float(decomp.get("serve_prefill_dispatch_ms",
                                             0.0)),
        "decode_dispatch": float(decomp.get("serve_decode_dispatch_ms",
                                            0.0)),
        "fetch": float(decomp.get("serve_fetch_ms", 0.0)),
        "commit": float(decomp.get("serve_commit_ms", 0.0)),
    }
    round_ms = float(decomp.get("serve_round_ms", 0.0))
    tokens = float(decomp.get("serve_tokens", 0.0))
    out: Dict[str, Any] = {
        "serve_rounds": float(decomp.get("serve_rounds", 0.0)),
        "serve_phases_ms": {k: round(v, 3) for k, v in phases.items()},
        "serve_round_ms": round(round_ms, 3),
        "serve_tokens": tokens,
    }
    if round_ms > 0 and out["serve_rounds"] > 0:
        fr = {k: v / round_ms for k, v in phases.items()}
        top = sorted(phases, key=phases.get, reverse=True)
        out["serve_phase_fractions"] = {k: round(v, 4)
                                        for k, v in fr.items()}
        out["serve_dominant_phase"] = top[0]
        out["serve_bound"] = SERVING_BOUND[top[0]]
        out["serve_phase_top2"] = [
            {"phase": k, "ms": round(phases[k], 3),
             "fraction": round(fr[k], 4)} for k in top[:2]]
        if tokens > 0:
            out["serve_ms_per_token"] = round(round_ms / tokens, 4)
    for k in ("trace_armed", "trace_device_syncs",
              "serve_phase_stall_events", "serve_trace_overhead_pct"):
        if k in decomp:
            out[k] = decomp[k]
    return out


def gate_serving(diag: Dict[str, Any], *,
                 max_phase_fraction: float = SERVING_MAX_PHASE_FRACTION,
                 max_trace_overhead_pct: float = TRACE_MAX_OVERHEAD_PCT,
                 program: str = "serving_round") -> Report:
    """The serving rules, in the graft-lint mold (exit status = CI gate):

    * ``serving-phase-stall`` — a NON-fetch phase exceeds
      ``max_phase_fraction`` of round wall time (corpus twin:
      ``serving-blind-stall``). Fails CLOSED when the decomposition
      carries no priced rounds — a gate that never saw a round must not
      certify the loop.
    * ``tracing-sync-leak`` — the tracer self-reports device syncs (a
      ``device_get`` per span — the defect its host-clock contract
      forbids), or measured tracing overhead reaches
      ``max_trace_overhead_pct`` (corpus twin: ``tracing-sync-leak``)."""
    report = Report(meta={"tool": "perf-doctor", "program": program,
                          "serving": diag})
    fr = diag.get("serve_phase_fractions")
    if not fr:
        report.extend([Finding(
            rule="serving-phase-stall",
            message="serving phases cannot be priced: the decomposition "
                    "carries no rounds / round wall time (serve some "
                    "load, then pass phase_decomposition() output)",
            program=program, ident="unpriced", data=dict(diag))])
        return report
    for phase, f in sorted(fr.items(), key=lambda kv: -kv[1]):
        if phase == "fetch":
            continue      # the one sync: device-bound is health
        if f > max_phase_fraction:
            report.extend([Finding(
                rule="serving-phase-stall",
                message=(f"serving rounds are {SERVING_BOUND[phase]}: "
                         f"phase '{phase}' takes {f:.0%} of round wall "
                         f"time (budget {max_phase_fraction:.0%}) — "
                         f"{SERVING_KNOBS[phase]}"),
                program=program, ident=phase,
                data={"phase": phase, "fraction": round(f, 4),
                      "phases_ms": diag.get("serve_phases_ms")})])
            break         # name the dominant stall, not every echo of it
    syncs = diag.get("trace_device_syncs") or 0
    pct = diag.get("serve_trace_overhead_pct")
    if syncs:
        report.extend([Finding(
            rule="tracing-sync-leak",
            message=(f"request tracing performed {int(syncs)} device "
                     "syncs — span bookkeeping must be host-wall-clock "
                     "only (a device_get per span serializes the exact "
                     "dispatch pipeline tracing exists to observe)"),
            program=program, ident="device-syncs",
            data={"trace_device_syncs": syncs})])
    elif pct is not None and float(pct) >= max_trace_overhead_pct:
        report.extend([Finding(
            rule="tracing-sync-leak",
            message=(f"request tracing adds {float(pct):.2f}% round time "
                     f"(budget < {max_trace_overhead_pct:.0f}%) — the "
                     "on_span hook is doing non-trivial work per span"),
            program=program, ident="overhead",
            data={"serve_trace_overhead_pct": pct})])
    return report


def serving_fields(diag: Dict[str, Any]) -> Dict[str, Any]:
    """The bench-JSON fields for the serving attribution (the doctor's
    bound + top-2 phases ride next to the SLO numbers)."""
    keys = ("serve_bound", "serve_dominant_phase", "serve_phase_top2",
            "serve_ms_per_token")
    return {k: diag[k] for k in keys if k in diag}


def baseline_dict(diag: Dict[str, Any]) -> Dict[str, Any]:
    return {"buckets": diag.get("buckets", {}),
            "stall_top2": diag.get("stall_top2", []),
            "exposed_comm_ms": diag.get("exposed_comm_ms", 0.0),
            "step_span_ms": diag.get("step_span_ms", 0.0)}


def stall_fields(diag: Dict[str, Any], suffix: str) -> Dict[str, Any]:
    """The bench-JSON fields: stall_top2_<suffix> = [{bucket, ms,
    fraction}, ...] (fraction is of step_span_ms)."""
    return {f"stall_top2_{suffix}": [
        {"bucket": s["bucket"], "ms": s["ms"], "fraction": s["fraction"]}
        for s in diag.get("stall_top2", [])]}


# --------------------------------------------------------------------------
# seeded corpus
# --------------------------------------------------------------------------

def synthetic_exposed_collective_trace() -> Dict[str, Any]:
    """A trace with an artificially exposed collective: 10 ms of matmul,
    then an 8 ms all-reduce with NOTHING scheduled under it. Attribution
    must price the full 8 ms as exposed and the doctor gate must fire."""
    evs = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 10_000.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 2_000.0, "dur": 1_000.0,
         "name": "fusion.2", "args": {"hlo_op": "fusion.2"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 10_050.0, "dur": 8_000.0,
         "name": "all-reduce.3", "args": {"hlo_op": "all-reduce.3"}},
    ]
    return {"displayTimeUnit": "ms", "traceEvents": evs}


def synthetic_serialized_backward_trace() -> Dict[str, Any]:
    """The measured face of the ``serialized-backward`` defect (lint twin:
    analysis/corpus.py): the backward's attention/MLP matmuls run, then the
    tensor-axis reduction of the row-parallel projection crosses the wire
    with NOTHING scheduled under it — the chunked collective-matmul overlap
    path is silently off, so 6 ms of the 16 ms step is serial wire. The
    attribution must price the full collective as exposed and
    ``exposed-collective-measured`` must fire."""
    evs = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 4_000.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},           # attn bwd
        {"ph": "X", "pid": 1, "tid": 1, "ts": 4_100.0, "dur": 5_500.0,
         "name": "dot.2", "args": {"hlo_op": "dot.2"}},           # mlp bwd
        {"ph": "X", "pid": 1, "tid": 1, "ts": 9_700.0, "dur": 6_000.0,
         "name": "all-reduce.3", "args": {"hlo_op": "all-reduce.3"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 15_750.0, "dur": 250.0,
         "name": "fusion.4", "args": {"hlo_op": "fusion.4"}},     # epilogue
    ]
    return {"displayTimeUnit": "ms", "traceEvents": evs}


def simulate_serving_decomp(stalled: bool = False) -> Dict[str, Any]:
    """A synthetic 64-round phase decomposition in the ring's schema.
    Healthy: fetch-dominant (the round's one sync waits ~3.2 ms of a
    ~5.7 ms round on the device — the steady state the gate must PASS).
    ``stalled``: every other round pays an ~18 ms cold adapter page-in,
    so housekeeping swamps the round — the ``serving-blind-stall`` face
    the gate must name as paging-bound."""
    rounds = 64
    per = {"schedule": 0.35, "housekeeping": 0.15, "prefill_dispatch": 0.45,
           "decode_dispatch": 1.1, "fetch": 3.2, "commit": 0.25}
    totals = {k: v * rounds for k, v in per.items()}
    if stalled:
        totals["housekeeping"] += 18.0 * (rounds // 2)
    round_ms = sum(totals.values()) + 0.02 * rounds   # loop overhead
    return {
        "serve_rounds": float(rounds),
        "serve_schedule_ms": totals["schedule"],
        "serve_housekeeping_ms": totals["housekeeping"],
        "serve_prefill_dispatch_ms": totals["prefill_dispatch"],
        "serve_decode_dispatch_ms": totals["decode_dispatch"],
        "serve_fetch_ms": totals["fetch"],
        "serve_commit_ms": totals["commit"],
        "serve_round_ms": round_ms,
        "serve_tokens": float(rounds * 24),
    }


def audit_serving(stalled: bool = True) -> Report:
    """Corpus face of the serving gate: the stalled decomposition MUST
    fire ``serving-phase-stall`` naming housekeeping/paging; the healthy
    twin MUST pass (fetch-dominant is the certified steady state)."""
    diag = diagnose_serving(simulate_serving_decomp(stalled=stalled))
    return gate_serving(diag, program=("serving_blind_stall" if stalled
                                       else "serving_instrumented"))


def audit_tracing(leaky: bool = True) -> Report:
    """Corpus face of the tracing-overhead gate, driven through the REAL
    ``RequestTracer`` over a simulated request load. The leaky twin
    plants the defect the host-clock contract forbids: an ``on_span``
    hook that round-trips the device per span (one ``device_get`` each,
    self-reported on ``tracer.device_syncs`` per the hook contract) —
    the gate fires on the sync count, deterministically, with the
    measured per-span cost priced against the synthetic healthy round
    for the overhead field. The host-clock twin's hook is pure host work
    and MUST pass."""
    import time

    from deepspeed_tpu.telemetry.request_trace import RequestTracer

    tracer = RequestTracer(replica="audit")
    if leaky:
        import jax
        import jax.numpy as jnp

        def leak(ev):
            jax.device_get(jnp.zeros(()))   # the defect: a sync per span
            tracer.device_syncs += 1        # the hook self-report contract
        tracer.on_span = leak
    t0 = time.perf_counter()
    for rid in range(8):
        tracer.begin(rid)
        with tracer.span(rid, "prefill"):
            pass
        for _ in range(24):
            with tracer.span(rid, "decode_quantum"):
                pass
        tracer.instant(rid, "finish")
        tracer.end(rid)
    span_ms = (time.perf_counter() - t0) * 1e3
    decomp = simulate_serving_decomp(stalled=False)
    decomp["trace_armed"] = 1.0
    decomp["trace_device_syncs"] = float(tracer.device_syncs)
    decomp["serve_trace_overhead_pct"] = round(
        100.0 * span_ms / decomp["serve_round_ms"], 3)
    diag = diagnose_serving(decomp)
    return gate_serving(diag, program=("tracing_sync_leak" if leaky
                                       else "tracing_host_clock"))


DOCTOR_CORPUS = {
    "exposed-collective-trace": (synthetic_exposed_collective_trace,
                                 "exposed_collective_trace"),
    "serialized-backward": (synthetic_serialized_backward_trace,
                            "serialized_backward"),
}

# serving-tier entries run their own audit (decomp/tracer-driven, not a
# Chrome trace) — run_corpus_entry dispatches on membership
SERVING_CORPUS = {
    "serving-blind-stall": (lambda: audit_serving(stalled=True),
                            "serving_blind_stall"),
    "tracing-sync-leak": (lambda: audit_tracing(leaky=True),
                          "tracing_sync_leak"),
}


def run_corpus_entry(name: str = "exposed-collective-trace") -> Report:
    """A ``doctor`` corpus entry (analysis.corpus wires them into the lint
    --corpus runner): the seeded defect MUST fire its gate."""
    if name in SERVING_CORPUS:
        run, _program = SERVING_CORPUS[name]
        return run()
    make_trace, program = DOCTOR_CORPUS[name]
    diag = diagnose(make_trace())
    return gate(diag, program=program)


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _load_json(path: str) -> Dict[str, Any]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def _load_text(path: str) -> str:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return f.read()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.profiling.doctor",
        description="Stall attribution + CI gate over a jax.profiler traced "
                    "step (see profiling/capture.py for producing one).")
    p.add_argument("--trace", help="trace artifact (.json or .json.gz, "
                                   "Chrome-trace format)")
    p.add_argument("--hlo", help="compiled step program text (the "
                                 "trace_<tag>.hlo.txt.gz written next to "
                                 "the artifact) for the scope/census join")
    p.add_argument("--steps", type=int, default=None,
                   help="engine steps inside the capture window (default: "
                        "the artifact's recorded metadata.steps, else 1)")
    p.add_argument("--modeled-exposed-ms", type=float, default=None,
                   help="modeled exposed_comm_ms from the telemetry overlap "
                        "join, for the divergence cross-check")
    p.add_argument("--max-exposed-frac", type=float,
                   default=MAX_EXPOSED_COMM_FRACTION)
    p.add_argument("--json", dest="json_out", metavar="PATH",
                   help="write the diagnosis JSON to PATH ('-' for stdout)")
    p.add_argument("--baseline", help="baseline JSON: gate bucket fractions "
                                      "against it")
    p.add_argument("--write-baseline", metavar="PATH",
                   help="accept the current attribution and exit 0")
    p.add_argument("--corpus", help="run a seeded known-bad entry instead "
                                    "of a trace (doctor gate self-test)")
    p.add_argument("--offload-decomp", metavar="PATH",
                   help="offload decomposition JSON (the "
                        "measure_decomposition fields + offload_step_ms, "
                        "e.g. cut from the bench JSON): run the "
                        "offload-overlap gate instead of a trace")
    p.add_argument("--min-offload-overlap", type=float,
                   default=OFFLOAD_MIN_OVERLAP)
    p.add_argument("--serving-decomp", metavar="PATH",
                   help="serving round-phase decomposition JSON "
                        "(ServingEngine.phase_decomposition() output, e.g. "
                        "cut from the bench JSON): run the "
                        "serving-phase-stall / tracing-sync-leak gates "
                        "instead of a trace")
    p.add_argument("--max-phase-fraction", type=float,
                   default=SERVING_MAX_PHASE_FRACTION)
    args = p.parse_args(argv)

    if args.serving_decomp:
        decomp = _load_json(args.serving_decomp)
        diag = diagnose_serving(decomp)
        report = gate_serving(
            diag, max_phase_fraction=args.max_phase_fraction,
            program=os.path.basename(args.serving_decomp))
        print(report.summary(), file=sys.stderr)
        top = ", ".join(f"{s['phase']}={s['ms']:.2f}ms({s['fraction']:.0%})"
                        for s in diag.get("serve_phase_top2", [])) or "none"
        print(f"doctor: {diag.get('serve_rounds', 0):.0f} serving rounds, "
              f"bound {diag.get('serve_bound', 'unpriced')}, top phases: "
              f"{top}", file=sys.stderr)
        if args.json_out:
            payload = dict(diag)
            payload["findings"] = [f.to_dict() for f in report.findings]
            payload["ok"] = report.ok
            text = json.dumps(payload, indent=2, default=str)
            if args.json_out == "-":
                print(text)
            else:
                with open(args.json_out, "w") as f:
                    f.write(text + "\n")
        return 0 if report.ok else 1

    if args.offload_decomp:
        decomp = _load_json(args.offload_decomp)
        diag = diagnose_offload(decomp)
        report = gate_offload(diag,
                              min_overlap=args.min_offload_overlap,
                              program=os.path.basename(args.offload_decomp))
        print(report.summary(), file=sys.stderr)
        if args.json_out:
            payload = dict(diag)
            payload["findings"] = [f.to_dict() for f in report.findings]
            payload["ok"] = report.ok
            text = json.dumps(payload, indent=2, default=str)
            if args.json_out == "-":
                print(text)
            else:
                with open(args.json_out, "w") as f:
                    f.write(text + "\n")
        return 0 if report.ok else 1

    if args.corpus:
        name = ("exposed-collective-trace" if args.corpus == "doctor"
                else args.corpus)
        if name not in DOCTOR_CORPUS and name not in SERVING_CORPUS:
            p.error(f"unknown doctor corpus entry '{args.corpus}' — one of "
                    f"{sorted({**DOCTOR_CORPUS, **SERVING_CORPUS})}")
        report = run_corpus_entry(name)
        print(report.summary(), file=sys.stderr)
        return 0 if report.ok else 1
    if not args.trace:
        p.error("--trace (or --corpus) is required")

    trace = _load_json(args.trace)
    hlo_path = args.hlo
    if hlo_path is None:
        guess = args.trace.replace(".json.gz", ".hlo.txt.gz") \
                          .replace(".json", ".hlo.txt.gz")
        hlo_path = guess if os.path.exists(guess) else None
    hlo_text = _load_text(hlo_path) if hlo_path else ""
    steps = args.steps
    if steps is None:   # an explicit --steps wins over the recorded value
        meta = trace.get("metadata") if isinstance(trace, dict) else None
        steps = int(meta["steps"]) if meta and meta.get("steps") else 1
    diag = diagnose(trace, hlo_text, steps=steps,
                    modeled_exposed_comm_ms=args.modeled_exposed_ms)
    baseline = _load_json(args.baseline) if args.baseline else None
    report = gate(diag, baseline=baseline,
                  max_exposed_fraction=args.max_exposed_frac,
                  program=os.path.basename(args.trace))

    print(report.summary(), file=sys.stderr)
    top = ", ".join(f"{s['bucket']}={s['ms']:.2f}ms({s['fraction']:.0%})"
                    for s in diag["stall_top2"]) or "none"
    print(f"doctor: step {diag['step_span_ms']:.3f} ms, device busy "
          f"{diag['device_busy_ms']:.3f} ms, top stalls: {top}",
          file=sys.stderr)
    if args.json_out:
        payload = dict(diag)
        payload["findings"] = [f.to_dict() for f in report.findings]
        payload["ok"] = report.ok
        text = json.dumps(payload, indent=2, default=str)
        if args.json_out == "-":
            print(text)
        else:
            with open(args.json_out, "w") as f:
                f.write(text + "\n")
    if args.write_baseline:
        with open(args.write_baseline, "w") as f:
            json.dump(baseline_dict(diag), f, indent=2)
        print(f"doctor: baseline written to {args.write_baseline}",
              file=sys.stderr)
        return 0
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
