"""Perf doctor: machine-readable diagnosis + CI gate over a traced step.

Turns a captured trace artifact (profiling/capture.py) into the same
finding/baseline machinery graft-lint uses, so a perf regression gates a
pipeline exactly like a collective-census drift does::

    python -m deepspeed_tpu.profiling.doctor --trace bench_artifacts/trace_seq2048.json.gz
    python -m deepspeed_tpu.profiling.doctor --trace T --write-baseline doctor_baseline.json
    python -m deepspeed_tpu.profiling.doctor --trace T --baseline doctor_baseline.json
    python -m deepspeed_tpu.profiling.doctor --corpus exposed-collective-trace

Rules:

  * ``stall-regression``      — a bucket's fraction of step time grew past
                                the baseline by more than the tolerance
  * ``exposed-collective-measured`` — measured exposed-comm time exceeds
                                the allowed fraction of the step (the
                                default gate; fires with no baseline)
  * ``modeled-measured-divergence`` — measured exposed-comm ms diverges
                                from the static OverlapAudit's modeled
                                ``exposed_comm_ms`` by > 25% (warning: one
                                of the two models is lying)

Exit status: non-zero when any error finding survives — the CI gate.
"""

import argparse
import gzip
import json
import os
import sys
from typing import Any, Dict, List, Optional

from deepspeed_tpu.analysis.report import Finding, Report
from deepspeed_tpu.profiling import trace_analysis
from deepspeed_tpu.profiling.trace_analysis import (classify_bounds,
                                                    join_census,
                                                    stall_ranking, stall_top2)

# measured exposed collective time above this fraction of the step is an
# error even without a baseline — wire latency the scheduler is not hiding
MAX_EXPOSED_COMM_FRACTION = 0.15
# modeled (OverlapAudit) vs measured exposed-comm divergence warning bar
DIVERGENCE_TOLERANCE = 0.25
# baseline gating: a bucket must grow BOTH 20% relative and 2 points of
# step fraction before stall-regression fires (absolute floor keeps noise
# on tiny buckets from gating)
REGRESSION_REL = 0.20
REGRESSION_ABS = 0.02
# offload pipeline gate: the measured share of the streamed step's storage
# IO the executor hid under compute (bench: offload_overlap_fraction).
# Below this the capacity rung is paying serialized wire/host time the
# three-way read || update || write schedule exists to hide.
OFFLOAD_MIN_OVERLAP = 0.8


def diagnose(trace: Any, hlo_text: str = "", *,
             cost: Optional[Dict[str, Any]] = None,
             steps: int = 1,
             modeled_exposed_comm_ms: Optional[float] = None,
             accel=None) -> Dict[str, Any]:
    """Full attribution + roofline + census join + top-2 stalls for one
    traced step. Pure host work — no jax import on the happy path."""
    if accel is None:
        from deepspeed_tpu.accelerator import get_accelerator
        accel = get_accelerator()
    scope_map = (trace_analysis.parse_hlo_scopes(hlo_text)
                 if hlo_text else None)
    attr = trace_analysis.attribute(trace, scope_map, steps=steps)
    bounds = classify_bounds(
        attr, cost,
        peak_flops=accel.peak_flops_per_device("bf16"),
        hbm_bytes_per_sec=accel.hbm_bytes_per_sec())
    out = {
        "step_span_ms": round(attr.step_span_ms, 4),
        "device_busy_ms": round(attr.device_busy_ms, 4),
        "fwd_ms": round(attr.fwd_ms, 4),
        "bwd_ms": round(attr.bwd_ms, 4),
        "buckets": attr.buckets,
        "bounds": bounds,
        "by_scope_ms": {k: round(v, 4) for k, v in sorted(
            attr.by_scope_ms.items(), key=lambda kv: -kv[1])},
        "exposed_comm_ms": round(attr.exposed_comm_ms, 4),
        "stalls": stall_ranking(attr, bounds),
        "stall_top2": stall_top2(attr, bounds),
        "joined_ops": attr.joined_ops,
        "total_ops": attr.total_ops,
    }
    if cost and cost.get("census"):
        out["collective_join"] = join_census(attr, cost["census"])
    if modeled_exposed_comm_ms is not None:
        out["modeled_exposed_comm_ms"] = round(modeled_exposed_comm_ms, 4)
        hi = max(attr.exposed_comm_ms, modeled_exposed_comm_ms)
        div = (abs(attr.exposed_comm_ms - modeled_exposed_comm_ms) / hi
               if hi > 0 else 0.0)
        out["exposed_comm_divergence"] = round(div, 4)
    return out


def gate(diag: Dict[str, Any], *,
         baseline: Optional[Dict[str, Any]] = None,
         max_exposed_fraction: float = MAX_EXPOSED_COMM_FRACTION,
         program: str = "traced_step") -> Report:
    """Apply the doctor's gating rules to a diagnosis. Returns a Report in
    the graft-lint mold: ``report.ok`` is the exit status, findings carry
    rule/ident for baseline suppression."""
    report = Report(meta={"tool": "perf-doctor", "program": program,
                          "step_span_ms": diag.get("step_span_ms")})
    span = diag.get("step_span_ms") or 0.0
    exposed = diag.get("exposed_comm_ms") or 0.0
    if span > 0 and exposed / span > max_exposed_fraction:
        report.extend([Finding(
            rule="exposed-collective-measured",
            message=(f"measured exposed collective time {exposed:.3f} ms is "
                     f"{exposed / span:.1%} of the {span:.3f} ms step "
                     f"(budget {max_exposed_fraction:.0%}) — the scheduler "
                     "is not hiding this wire time under compute"),
            program=program, ident="exposed",
            data={"exposed_comm_ms": exposed, "step_span_ms": span})])
    div = diag.get("exposed_comm_divergence")
    if div is not None and div > DIVERGENCE_TOLERANCE:
        report.extend([Finding(
            rule="modeled-measured-divergence", severity="warning",
            message=(f"measured exposed-comm {exposed:.3f} ms vs modeled "
                     f"{diag.get('modeled_exposed_comm_ms'):.3f} ms diverge "
                     f"{div:.0%} (> {DIVERGENCE_TOLERANCE:.0%}) — the "
                     "overlap model or the interconnect pricing is off"),
            program=program, ident="divergence",
            data={"divergence": div})])
    if baseline:
        base_buckets = baseline.get("buckets", {})
        for name, stat in diag.get("buckets", {}).items():
            base = base_buckets.get(name)
            if base is None:
                continue
            cur_f, base_f = stat["fraction"], base.get("fraction", 0.0)
            if (cur_f - base_f > REGRESSION_ABS
                    and cur_f > base_f * (1 + REGRESSION_REL)):
                report.extend([Finding(
                    rule="stall-regression",
                    message=(f"bucket '{name}' grew to {cur_f:.1%} of the "
                             f"step (baseline {base_f:.1%}) — attribution "
                             "regression"),
                    program=program, ident=name,
                    data={"fraction": cur_f, "baseline": base_f})])
    return report


def diagnose_offload(decomp: Dict[str, Any],
                     step_ms: Optional[float] = None) -> Dict[str, Any]:
    """Host-stall attribution for the offload phases of a layer-streamed
    step, from the measured decomposition
    (``InfinityExecutor.measure_decomposition``) plus a measured step time.

    Attribution: compute = L x (layer fwd+bwd) + L x (chunk Adam) + the
    embed/CE-head top; io = 2L param-chunk fetches + L opt-chunk
    round-trips; everything the step spent beyond compute is EXPOSED io/
    host stall (clamped to the io budget), and
    ``offload_overlap_fraction = 1 - exposed/io`` prices how much of the
    storage traffic the pipeline actually hid under compute."""
    compute = (float(decomp.get("offload_compute_ms", 0.0))
               + float(decomp.get("offload_update_sweep_ms", 0.0))
               + float(decomp.get("offload_top_ms", 0.0)))
    io = float(decomp.get("offload_io_ms")
               or decomp.get("offload_dma_ms") or 0.0)
    out: Dict[str, Any] = {
        "offload_compute_total_ms": round(compute, 2),
        "offload_io_ms": round(io, 2),
        "offload_pipeline": decomp.get("offload_pipeline"),
    }
    if step_ms is None:
        step_ms = decomp.get("offload_step_ms")
    if step_ms:
        exposed = max(0.0, min(float(step_ms) - compute, io))
        out["offload_step_ms"] = round(float(step_ms), 2)
        out["offload_exposed_io_ms"] = round(exposed, 2)
        out["offload_overlap_fraction"] = (round(1.0 - exposed / io, 4)
                                           if io > 0 else 1.0)
        # which phase dominates the step — the "turn this knob" signal
        phases = {"layer-compute": float(decomp.get("offload_compute_ms",
                                                    0.0)),
                  "host-adam": float(decomp.get("offload_update_sweep_ms",
                                                0.0)),
                  "top-compute": float(decomp.get("offload_top_ms", 0.0)),
                  "exposed-io-stall": exposed}
        out["offload_dominant_phase"] = max(phases, key=phases.get)
    elif "offload_overlap_fraction" in decomp:
        out["offload_overlap_fraction"] = decomp["offload_overlap_fraction"]
    return out


def gate_offload(diag: Dict[str, Any], *,
                 min_overlap: float = OFFLOAD_MIN_OVERLAP,
                 program: str = "offload_step") -> Report:
    """The ``offload-overlap`` rule: the streamed step left more than
    (1 - min_overlap) of its storage IO exposed — the executor is running
    fetch -> compute -> host-Adam -> write-back serially instead of the
    three-way pipeline. Report in the graft-lint mold (exit status = CI
    gate); the corpus twin is ``offload-serial-pipeline``."""
    report = Report(meta={"tool": "perf-doctor", "program": program,
                          "offload": diag})
    frac = diag.get("offload_overlap_fraction")
    if frac is None:
        # fail CLOSED: a gate that cannot price the overlap (no
        # offload_step_ms / offload_overlap_fraction in the input) must
        # not certify the pipeline it never measured
        report.extend([Finding(
            rule="offload-overlap",
            message="offload overlap cannot be priced: the decomposition "
                    "carries no offload_overlap_fraction and no "
                    "offload_step_ms (pass the measured step time "
                    "alongside the measure_decomposition fields)",
            program=program, ident="unpriced", data=dict(diag))])
        return report
    if frac < min_overlap:
        exposed = diag.get("offload_exposed_io_ms", 0.0)
        io = diag.get("offload_io_ms", 0.0)
        report.extend([Finding(
            rule="offload-overlap",
            message=(f"offload pipeline hid only {frac:.0%} of the streamed "
                     f"step's {io:.1f} ms storage IO under compute (budget "
                     f"{min_overlap:.0%}; {exposed:.1f} ms exposed host "
                     f"stall, dominant phase "
                     f"{diag.get('offload_dominant_phase', 'unknown')}) — "
                     "check offload_param/offload_optimizer "
                     "pipeline_read/pipeline_write and the aio "
                     "read_queue_depth/write_queue_depth"),
            program=program, ident="offload-overlap",
            data={"stall": "host-io", **diag})])
    return report


def offload_fields(diag: Dict[str, Any]) -> Dict[str, Any]:
    """The bench-JSON fields for the offload attribution."""
    keys = ("offload_overlap_fraction", "offload_exposed_io_ms",
            "offload_io_ms", "offload_dominant_phase")
    return {k: diag[k] for k in keys if k in diag}


def baseline_dict(diag: Dict[str, Any]) -> Dict[str, Any]:
    return {"buckets": diag.get("buckets", {}),
            "stall_top2": diag.get("stall_top2", []),
            "exposed_comm_ms": diag.get("exposed_comm_ms", 0.0),
            "step_span_ms": diag.get("step_span_ms", 0.0)}


def stall_fields(diag: Dict[str, Any], suffix: str) -> Dict[str, Any]:
    """The bench-JSON fields: stall_top2_<suffix> = [{bucket, ms,
    fraction}, ...] (fraction is of step_span_ms)."""
    return {f"stall_top2_{suffix}": [
        {"bucket": s["bucket"], "ms": s["ms"], "fraction": s["fraction"]}
        for s in diag.get("stall_top2", [])]}


# --------------------------------------------------------------------------
# seeded corpus
# --------------------------------------------------------------------------

def synthetic_exposed_collective_trace() -> Dict[str, Any]:
    """A trace with an artificially exposed collective: 10 ms of matmul,
    then an 8 ms all-reduce with NOTHING scheduled under it. Attribution
    must price the full 8 ms as exposed and the doctor gate must fire."""
    evs = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 10_000.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},
        {"ph": "X", "pid": 1, "tid": 2, "ts": 2_000.0, "dur": 1_000.0,
         "name": "fusion.2", "args": {"hlo_op": "fusion.2"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 10_050.0, "dur": 8_000.0,
         "name": "all-reduce.3", "args": {"hlo_op": "all-reduce.3"}},
    ]
    return {"displayTimeUnit": "ms", "traceEvents": evs}


def synthetic_serialized_backward_trace() -> Dict[str, Any]:
    """The measured face of the ``serialized-backward`` defect (lint twin:
    analysis/corpus.py): the backward's attention/MLP matmuls run, then the
    tensor-axis reduction of the row-parallel projection crosses the wire
    with NOTHING scheduled under it — the chunked collective-matmul overlap
    path is silently off, so 6 ms of the 16 ms step is serial wire. The
    attribution must price the full collective as exposed and
    ``exposed-collective-measured`` must fire."""
    evs = [
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 4_000.0,
         "name": "dot.1", "args": {"hlo_op": "dot.1"}},           # attn bwd
        {"ph": "X", "pid": 1, "tid": 1, "ts": 4_100.0, "dur": 5_500.0,
         "name": "dot.2", "args": {"hlo_op": "dot.2"}},           # mlp bwd
        {"ph": "X", "pid": 1, "tid": 1, "ts": 9_700.0, "dur": 6_000.0,
         "name": "all-reduce.3", "args": {"hlo_op": "all-reduce.3"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 15_750.0, "dur": 250.0,
         "name": "fusion.4", "args": {"hlo_op": "fusion.4"}},     # epilogue
    ]
    return {"displayTimeUnit": "ms", "traceEvents": evs}


DOCTOR_CORPUS = {
    "exposed-collective-trace": (synthetic_exposed_collective_trace,
                                 "exposed_collective_trace"),
    "serialized-backward": (synthetic_serialized_backward_trace,
                            "serialized_backward"),
}


def run_corpus_entry(name: str = "exposed-collective-trace") -> Report:
    """A ``doctor`` corpus entry (analysis.corpus wires them into the lint
    --corpus runner): the seeded exposed collective MUST fire the gate."""
    make_trace, program = DOCTOR_CORPUS[name]
    diag = diagnose(make_trace())
    return gate(diag, program=program)


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _load_json(path: str) -> Dict[str, Any]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def _load_text(path: str) -> str:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return f.read()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.profiling.doctor",
        description="Stall attribution + CI gate over a jax.profiler traced "
                    "step (see profiling/capture.py for producing one).")
    p.add_argument("--trace", help="trace artifact (.json or .json.gz, "
                                   "Chrome-trace format)")
    p.add_argument("--hlo", help="compiled step program text (the "
                                 "trace_<tag>.hlo.txt.gz written next to "
                                 "the artifact) for the scope/census join")
    p.add_argument("--steps", type=int, default=None,
                   help="engine steps inside the capture window (default: "
                        "the artifact's recorded metadata.steps, else 1)")
    p.add_argument("--modeled-exposed-ms", type=float, default=None,
                   help="modeled exposed_comm_ms from the telemetry overlap "
                        "join, for the divergence cross-check")
    p.add_argument("--max-exposed-frac", type=float,
                   default=MAX_EXPOSED_COMM_FRACTION)
    p.add_argument("--json", dest="json_out", metavar="PATH",
                   help="write the diagnosis JSON to PATH ('-' for stdout)")
    p.add_argument("--baseline", help="baseline JSON: gate bucket fractions "
                                      "against it")
    p.add_argument("--write-baseline", metavar="PATH",
                   help="accept the current attribution and exit 0")
    p.add_argument("--corpus", help="run a seeded known-bad entry instead "
                                    "of a trace (doctor gate self-test)")
    p.add_argument("--offload-decomp", metavar="PATH",
                   help="offload decomposition JSON (the "
                        "measure_decomposition fields + offload_step_ms, "
                        "e.g. cut from the bench JSON): run the "
                        "offload-overlap gate instead of a trace")
    p.add_argument("--min-offload-overlap", type=float,
                   default=OFFLOAD_MIN_OVERLAP)
    args = p.parse_args(argv)

    if args.offload_decomp:
        decomp = _load_json(args.offload_decomp)
        diag = diagnose_offload(decomp)
        report = gate_offload(diag,
                              min_overlap=args.min_offload_overlap,
                              program=os.path.basename(args.offload_decomp))
        print(report.summary(), file=sys.stderr)
        if args.json_out:
            payload = dict(diag)
            payload["findings"] = [f.to_dict() for f in report.findings]
            payload["ok"] = report.ok
            text = json.dumps(payload, indent=2, default=str)
            if args.json_out == "-":
                print(text)
            else:
                with open(args.json_out, "w") as f:
                    f.write(text + "\n")
        return 0 if report.ok else 1

    if args.corpus:
        name = ("exposed-collective-trace" if args.corpus == "doctor"
                else args.corpus)
        if name not in DOCTOR_CORPUS:
            p.error(f"unknown doctor corpus entry '{args.corpus}' — one of "
                    f"{sorted(DOCTOR_CORPUS)}")
        report = run_corpus_entry(name)
        print(report.summary(), file=sys.stderr)
        return 0 if report.ok else 1
    if not args.trace:
        p.error("--trace (or --corpus) is required")

    trace = _load_json(args.trace)
    hlo_path = args.hlo
    if hlo_path is None:
        guess = args.trace.replace(".json.gz", ".hlo.txt.gz") \
                          .replace(".json", ".hlo.txt.gz")
        hlo_path = guess if os.path.exists(guess) else None
    hlo_text = _load_text(hlo_path) if hlo_path else ""
    steps = args.steps
    if steps is None:   # an explicit --steps wins over the recorded value
        meta = trace.get("metadata") if isinstance(trace, dict) else None
        steps = int(meta["steps"]) if meta and meta.get("steps") else 1
    diag = diagnose(trace, hlo_text, steps=steps,
                    modeled_exposed_comm_ms=args.modeled_exposed_ms)
    baseline = _load_json(args.baseline) if args.baseline else None
    report = gate(diag, baseline=baseline,
                  max_exposed_fraction=args.max_exposed_frac,
                  program=os.path.basename(args.trace))

    print(report.summary(), file=sys.stderr)
    top = ", ".join(f"{s['bucket']}={s['ms']:.2f}ms({s['fraction']:.0%})"
                    for s in diag["stall_top2"]) or "none"
    print(f"doctor: step {diag['step_span_ms']:.3f} ms, device busy "
          f"{diag['device_busy_ms']:.3f} ms, top stalls: {top}",
          file=sys.stderr)
    if args.json_out:
        payload = dict(diag)
        payload["findings"] = [f.to_dict() for f in report.findings]
        payload["ok"] = report.ok
        text = json.dumps(payload, indent=2, default=str)
        if args.json_out == "-":
            print(text)
        else:
            with open(args.json_out, "w") as f:
                f.write(text + "\n")
    if args.write_baseline:
        with open(args.write_baseline, "w") as f:
            json.dump(baseline_dict(diag), f, indent=2)
        print(f"doctor: baseline written to {args.write_baseline}",
              file=sys.stderr)
        return 0
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
