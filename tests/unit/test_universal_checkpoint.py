"""Universal checkpoint: save at mesh A, resume at mesh B.

Reference: ``deepspeed/checkpoint/universal_checkpoint.py:10`` +
``reshape_3d_utils.py`` + ``tests/unit/model_parallelism/
test_configurable_parallel_mp.py`` (resize TP/PP on resume). The reference
needs explicit reshape tooling because its shards are rank-local files;
here Orbax stores the GLOBAL arrays, so restore-at-a-different-mesh is a
property to prove, not machinery to build. These tests prove it: the loss
trajectory after resume must match the original run continuing.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model

# quick tier: `pytest -m 'not slow'` skips this module (cross-mesh save/restore matrix compiles many mesh programs)
pytestmark = pytest.mark.slow


def _model():
    return make_model(TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
        max_seq_len=64, dtype=jnp.float32, attention_impl="xla",
        tie_embeddings=False), name="uckpt")


def _cfg(mesh_axes, gas=2, micro=2):
    dp = 1
    for ax, n in mesh_axes.items():
        if ax in ("data", "fsdp", "expert"):
            dp *= n
    return {
        "train_batch_size": micro * gas * dp,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3 if mesh_axes.get("fsdp", 1) > 1
                              else 1},
        "mesh": {"axes": mesh_axes},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000000,
    }


def _batch(B, S=64, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, (B, S), dtype=np.int32)}


def _engine(mesh_axes, devices, gas=2):
    cfg = _cfg(mesh_axes, gas=gas)
    engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg,
                                          devices=devices)
    return engine, cfg["train_batch_size"]


class TestCrossMeshCheckpoint:
    """The (tp, fsdp, pp)-degree-change matrix VERDICT r3 item 4 asks for."""

    def _save_and_ref(self, tmp_path, devices8, mesh_axes, steps=3, cont=2):
        engine, B = _engine(mesh_axes, devices8)
        batch = _batch(B)
        for _ in range(steps):
            engine.train_batch(batch)
        engine.save_checkpoint(str(tmp_path), tag="x")
        ref = [float(engine.train_batch(batch)["loss"])
               for _ in range(cont)]
        return ref, B

    def _resume(self, tmp_path, devices8, mesh_axes, B_ref, cont=2,
                devices=None, gas=2):
        engine, B = _engine(mesh_axes, devices if devices is not None
                            else devices8, gas=gas)
        assert B == B_ref, "global batch must match for trajectory parity"
        engine.load_checkpoint(str(tmp_path), tag="x")
        batch = _batch(B)
        return [float(engine.train_batch(batch)["loss"])
                for _ in range(cont)]

    def test_fsdp4_tp2_to_fsdp2_tp4(self, tmp_path, devices8):
        ref, B = self._save_and_ref(tmp_path, devices8,
                                    {"fsdp": 4, "tensor": 2})
        # dp halves (4 -> 2): keep the global batch with micro=4
        cfg = _cfg({"fsdp": 2, "tensor": 4}, gas=2, micro=4)
        assert cfg["train_batch_size"] == B
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg,
                                              devices=devices8)
        engine.load_checkpoint(str(tmp_path), tag="x")
        got = [float(engine.train_batch(_batch(B))["loss"])
               for _ in range(2)]
        np.testing.assert_allclose(ref, got, rtol=2e-4, atol=1e-5)

    def test_fsdp8_to_data2_fsdp4(self, tmp_path, devices8):
        ref, B = self._save_and_ref(tmp_path, devices8, {"fsdp": 8})
        got = self._resume(tmp_path, devices8, {"data": 2, "fsdp": 4}, B)
        np.testing.assert_allclose(ref, got, rtol=2e-4, atol=1e-5)

    def test_fsdp4_tp2_to_pipeline(self, tmp_path, devices8):
        """Resume a GSPMD-trained checkpoint under pipeline parallelism."""
        ref, B = self._save_and_ref(tmp_path, devices8,
                                    {"fsdp": 4, "tensor": 2})
        cfg = _cfg({"pipe": 2, "data": 4}, gas=2, micro=2)
        assert cfg["train_batch_size"] == B
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg,
                                              devices=devices8)
        engine.load_checkpoint(str(tmp_path), tag="x")
        got = [float(engine.train_batch(_batch(B))["loss"])
               for _ in range(2)]
        # 1F1B recomputes the same math; bf16-free model -> tight tol
        np.testing.assert_allclose(ref, got, rtol=5e-4, atol=5e-5)

    def test_mesh_shrink_8_to_2(self, tmp_path, devices8):
        ref, B = self._save_and_ref(tmp_path, devices8, {"fsdp": 8})
        cfg = _cfg({"fsdp": 2}, gas=2, micro=8)
        assert cfg["train_batch_size"] == B
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg,
                                              devices=devices8[:2])
        engine.load_checkpoint(str(tmp_path), tag="x")
        got = [float(engine.train_batch(_batch(B))["loss"])
               for _ in range(2)]
        np.testing.assert_allclose(ref, got, rtol=2e-4, atol=1e-5)


def test_inspect_cli(tmp_path, devices8, capsys):
    engine, B = _engine({"fsdp": 4, "tensor": 2}, devices8)
    engine.train_batch(_batch(B))
    engine.save_checkpoint(str(tmp_path), tag="t1")
    from deepspeed_tpu.utils.ckpt_tools import main
    main(["inspect", str(tmp_path)])
    out = capsys.readouterr().out
    assert "t1" in out and "params" in out


def test_validate_mesh_cli(tmp_path, devices8, capsys):
    engine, B = _engine({"fsdp": 4, "tensor": 2}, devices8)
    engine.train_batch(_batch(B))
    engine.save_checkpoint(str(tmp_path), tag="t1")
    from deepspeed_tpu.utils.ckpt_tools import main
    rc = main(["validate", str(tmp_path), "--mesh", "fsdp=2,tensor=4"])
    assert rc == 0
    rc = main(["validate", str(tmp_path), "--mesh", "tensor=3"])
    out = capsys.readouterr().out
    assert rc != 0 and "divis" in out.lower()
