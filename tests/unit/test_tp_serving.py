"""Pod-scale serving: TP-sharded paged decode + expert-parallel MoE
(ISSUE 15).

The serving engine is mesh-native: the paged KV block pools
``[L, NB, nkv, block_size, hd]`` shard on the kv-head dim over the
`tensor` mesh axis through the same Megatron col/row rules the weights
use, and the MoE FFN expert stacks shard over `expert`. The load-bearing
contracts pinned here:

  - a tp=2 serving engine's greedy outputs are TOKEN-IDENTICAL to the
    single-chip engine over the full workload (f32), including
    preemption/re-prefill resume, prefix-cache warm hits, chunked
    prefill and speculative decoding under sharding;
  - the per-round collective census of the tp=2 quantum step is pinned
    EXACTLY — the per-layer out-projection reductions (+ the vocab-
    sharded embed gather) are the only cross-chip collectives, the pool
    scatter contributes ZERO (`tp-serving-replicated-pool` corpus pins
    the replicated-pool drift defect both directions);
  - pool bytes price the PER-DEVICE shard (memory law:
    per_device * tp == logical), and every serving program's pool output
    is pinned to the head-sharded layout;
  - drains record the mesh topology (tp/ep); resume/accept_migration
    refuse a mesh-incompatible placement with the typed
    ``ResumeIncompatible`` (tp=2 -> tp=2 continues byte-identically,
    tp=2 -> tp=1 refuses loudly); replica heartbeats carry the topology.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference.serving import ResumeIncompatible
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.parallel import MeshPlan, build_mesh


def _cfg(**overrides):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, max_seq_len=256, position_type="rotary",
                activation="silu_glu", norm_type="rmsnorm",
                tie_embeddings=False, dtype=jnp.float32,
                attention_impl="xla")
    base.update(overrides)
    return TransformerConfig(**base)


def _mesh(n, **axes):
    return build_mesh(MeshPlan(**axes), devices=jax.devices()[:n])


def _serving(model, params, mesh=None, config=None, **serving):
    defaults = dict(max_seqs=2, block_size=16, max_model_len=128,
                    decode_quantum=4, prompt_bucket=16)
    defaults.update(serving)
    return deepspeed_tpu.init_serving(model, config=config or {},
                                      serving=defaults, dtype=jnp.float32,
                                      params=params, mesh=mesh)


def _reqs(seed=0, vocab=128, lens=(7, 21), news=(9, 6)):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, vocab, size=(n,)).astype(np.int32), k)
            for n, k in zip(lens, news)]


# ---------------------------------------------------------------------------
# tp=2 parity + pool sharding + the pool-bytes memory law
# ---------------------------------------------------------------------------

def test_tp2_token_identical_and_pool_bytes_law():
    """The headline ISSUE-15 contract: a tp=2 engine (pools head-sharded
    over `tensor`) produces exactly the single-chip greedy tokens, its
    pool output sharding survives serving rounds, and pool_bytes prices
    the PER-DEVICE shard — per_device * tp == logical, exactly (the
    memory-law style assert of the serve_pool_bytes fix)."""
    model = make_model(_cfg())
    params = jax.device_get(model.init(jax.random.PRNGKey(0)))
    reqs = _reqs()

    srv1 = _serving(model, params)
    outs1 = srv1.run(list(reqs))
    st1 = srv1.stats()
    assert (srv1.tp, srv1.ep) == (1, 1)
    assert st1["pool_bytes"] == st1["pool_bytes_logical"]

    srv2 = _serving(model, params, mesh=_mesh(2, tensor=2))
    assert (srv2.tp, srv2.ep) == (2, 1)
    assert srv2.mesh_desc == "tensor=2"
    # the pool shards on the kv-head dim (axis 2) over `tensor`
    spec = srv2.pools["k"].sharding.spec
    assert spec[2] == "tensor", spec
    shard = srv2.pools["k"].sharding.shard_shape(srv2.pools["k"].shape)
    assert shard[2] * 2 == srv2.pools["k"].shape[2]
    outs2 = srv2.run(list(reqs))
    for rid in outs1:
        np.testing.assert_array_equal(outs1[rid], outs2[rid],
                                      err_msg=f"request {rid}")
    st2 = srv2.stats()
    # memory law: the per-device shard is exactly logical / tp, and the
    # logical pool is mesh-independent
    assert st2["pool_bytes"] * 2 == st2["pool_bytes_logical"]
    assert st2["pool_bytes_logical"] == st1["pool_bytes_logical"]
    assert (st2["tp"], st2["ep"]) == (2.0, 1.0)
    # the out_shardings pin: after full serving rounds (prefill + quantum
    # steps + donations) the pool is still head-sharded, not replicated
    assert srv2.pools["k"].sharding.spec[2] == "tensor"


def test_ep4_moe_matches_unsharded():
    """Expert-parallel MoE serving: the Mixtral-family expert stacks
    shard over `expert` (dispatch/combine all-to-alls from the moe/
    constraints) and greedy outputs match the unsharded MoE engine
    token for token."""
    model = make_model(_cfg(num_experts=4, top_k=2))
    params = jax.device_get(model.init(jax.random.PRNGKey(1)))
    reqs = _reqs(seed=3)
    outs1 = _serving(model, params).run(list(reqs))
    srv4 = _serving(model, params, mesh=_mesh(4, expert=4))
    assert (srv4.tp, srv4.ep) == (1, 4)
    w = srv4.engine.params["layers"]["moe_w_in"]
    assert w.sharding.shard_shape(w.shape)[1] * 4 == w.shape[1]
    outs4 = srv4.run(list(reqs))
    for rid in outs1:
        np.testing.assert_array_equal(outs1[rid], outs4[rid],
                                      err_msg=f"request {rid}")


# ---------------------------------------------------------------------------
# mesh config validation
# ---------------------------------------------------------------------------

def test_kv_heads_must_divide_tp():
    model = make_model(_cfg(num_heads=6, num_kv_heads=3))
    with pytest.raises(ValueError, match="kv_heads"):
        _serving(model, None, mesh=_mesh(2, tensor=2))


def test_expert_parallel_needs_divisible_moe():
    dense = make_model(_cfg())
    with pytest.raises(ValueError, match="MoE"):
        deepspeed_tpu.init_inference(dense, config={"expert_parallel": 4},
                                     dtype=jnp.float32)
    moe = make_model(_cfg(num_experts=4, top_k=2))
    with pytest.raises(ValueError, match="num_experts"):
        deepspeed_tpu.init_inference(moe, config={"expert_parallel": 3},
                                     dtype=jnp.float32,
                                     mesh=_mesh(3, expert=3))


def test_mesh_contradicting_config_degree_refused():
    """An explicit mesh is authoritative; a config degree that contradicts
    it is a caller bug, not a silent replication."""
    model = make_model(_cfg())
    with pytest.raises(ValueError, match="tensor"):
        deepspeed_tpu.init_inference(model, config={"tensor_parallel": 4},
                                     dtype=jnp.float32,
                                     mesh=_mesh(2, tensor=2))


def test_dense_model_on_expert_mesh_degrades_not_crashes():
    """A SHARED mesh with an expert axis reused for a dense model must
    keep working (a dense model has no "expert" logical axis — nothing
    shards over it): ep degrades to 1 instead of the MoE validation
    firing, and the SERVING tier advertises the resolved degree (drains/
    heartbeats/migration must not claim expert sharding that does not
    exist — a dense survivor would be spuriously refused). Only an
    EXPLICIT expert_parallel request on a dense model is the caller bug
    that raises."""
    model = make_model(_cfg())
    params = jax.device_get(model.init(jax.random.PRNGKey(0)))
    eng = deepspeed_tpu.init_inference(model, config={},
                                       dtype=jnp.float32,
                                       mesh=_mesh(4, expert=4))
    assert eng.ep == 1
    srv = _serving(model, params, mesh=_mesh(4, expert=4))
    assert srv.ep == 1 and srv.tp == 1
    # migration between this engine and a plain dense engine is
    # geometry-compatible both ways
    srv.accept_migration([], geometry={"tp": 1, "ep": 1})
    with pytest.raises(ValueError, match="MoE"):
        deepspeed_tpu.init_inference(model, config={"expert_parallel": 4},
                                     dtype=jnp.float32,
                                     mesh=_mesh(4, expert=4))


def test_failover_prefers_geometry_matched_survivors(tmp_path):
    """The heartbeat tp/ep fields are load-bearing: _survivor_order ranks
    a geometry-matched survivor ahead of a less-loaded mismatched one (a
    mismatched survivor refuses drain-origin records typed anyway — the
    ordering skips the wasted round-trips); survivors without topology
    meta rank as matched (the typed refusal stays the arbiter)."""
    from deepspeed_tpu.analysis.serving_lint import _StubReplica
    from deepspeed_tpu.inference.router import RouterConfig, ServingRouter
    cfg = RouterConfig(store_dir=str(tmp_path / "store"),
                       drain_dir=str(tmp_path / "drains"))
    router = ServingRouter(cfg)
    for name in ("dead", "tp1", "tp2"):
        router.register_handle(_StubReplica(name, cfg.store_dir,
                                            cfg.drain_dir))
    # tp1 is the least loaded but mesh-mismatched; tp2 matches the drain
    router._info["tp1"] = {"ts": 0.0, "meta": {"tp": 1, "ep": 1,
                                               "queue_depth": 0,
                                               "running": 0,
                                               "capacity": 4}}
    router._info["tp2"] = {"ts": 0.0, "meta": {"tp": 2, "ep": 1,
                                               "queue_depth": 3,
                                               "running": 4,
                                               "capacity": 4}}
    order = [r.name for r in router._survivor_order(
        "dead", geometry={"tp": 2, "ep": 1})]
    assert order[0] == "tp2", order
    # without a drained geometry, plain load order wins — the order
    # _failover uses for resubmit-origin records, which regenerate from
    # scratch and must not skip a healthy idle survivor over a mesh
    # they don't care about
    order = [r.name for r in router._survivor_order("dead")]
    assert order[0] == "tp1", order


# ---------------------------------------------------------------------------
# collective census pin + the replicated-pool corpus twins
# ---------------------------------------------------------------------------

def test_tp2_census_pinned_exactly():
    """The tp=2 quantum step's per-round collective census, exact: 3
    all-reduces (the scanned layer body's attn/MLP out-projections + the
    vocab-sharded embed gather) and 2 tiny all-gathers (the greedy
    argmax's cross-shard (value, index) exchange). Nothing else — in
    particular ZERO collectives in the pool scatter: each chip writes its
    own head slice in place."""
    from deepspeed_tpu.analysis.corpus import (TP_SERVE_CENSUS,
                                               tp_serving_pool_report)
    rep = tp_serving_pool_report(shard_pool=True)
    assert rep.ok, [f.key for f in rep.findings]
    census = rep.census["serve_decode_step_tp2"]
    assert {k: v["count"] for k, v in census.items()} == TP_SERVE_CENSUS
    # the argmax exchange is control-plane tiny; every data-bearing
    # collective is an out-projection-shaped reduction
    assert census["all-gather"]["bytes"] <= 256


def test_tp_replicated_pool_corpus_both_directions():
    """The planted defect — KV pool replicated across `tensor` — must
    trip the replication budget AND the per-device memory peak AND drift
    the census (the fresh rows all-gather before the scatter); the
    head-sharded twin passes identical settings. Registered in the lint
    corpus (CLI: lint --corpus tp-serving-replicated-pool)."""
    from deepspeed_tpu.analysis.corpus import CORPUS, run_corpus
    assert "tp-serving-replicated-pool" in CORPUS
    bad = run_corpus("tp-serving-replicated-pool")
    assert not bad.ok
    rules = {f.rule for f in bad.findings}
    assert "replication-over-budget" in rules, rules
    assert "memory-peak" in rules, rules
    assert "collective-census-drift" in rules, rules


# ---------------------------------------------------------------------------
# mesh geometry: drains, migration, heartbeats
# ---------------------------------------------------------------------------

def test_drain_records_mesh_and_tp1_refuses(tmp_path):
    """Drain-state v2 records the mesh topology; a tp=1 engine resuming a
    tp=2 drain refuses with the typed ResumeIncompatible (continuation
    determinism is per-geometry), and a fresh tp=2 engine picks the work
    up. The replica heartbeat meta carries the same topology."""
    model = make_model(_cfg())
    params = jax.device_get(model.init(jax.random.PRNGKey(0)))
    srv2 = _serving(model, params, mesh=_mesh(2, tensor=2))
    srv2.add_request(np.arange(5, dtype=np.int32), 6)
    srv2.step()
    tag_dir = srv2.drain(str(tmp_path))

    import json
    import os
    with open(os.path.join(tag_dir, "state.json")) as f:
        state = json.load(f)
    assert state["engine"]["tp"] == 2 and state["engine"]["ep"] == 1

    srv1 = _serving(model, params)
    with pytest.raises(ResumeIncompatible, match="tp=2"):
        srv1.resume(str(tmp_path))
    # per-request migration applies the same check
    with pytest.raises(ResumeIncompatible, match="tp=2"):
        srv1.accept_migration(state["requests"],
                              geometry=state["engine"])
    # records that PREDATE the geometry fields interop (no refusal)
    legacy = {k: v for k, v in state["engine"].items()
              if k not in ("tp", "ep")}
    assert srv1.accept_migration(state["requests"], geometry=legacy)

    srv2b = _serving(model, params, mesh=_mesh(2, tensor=2))
    rids = srv2b.resume(str(tmp_path))
    assert rids == [state["requests"][0]["rid"]]

    # heartbeat meta: the router's registry sees the topology
    from deepspeed_tpu.inference.router import ReplicaHandle
    h = ReplicaHandle("r0", srv2b, str(tmp_path / "store"),
                      str(tmp_path / "drains"))
    meta = h.meta()
    assert meta["tp"] == 2 and meta["ep"] == 1


# ---------------------------------------------------------------------------
# slow: parity under preemption + prefix cache + latency tier, and the
# tp2 -> tp2 drained continuation
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_tp2_parity_under_preemption_and_prefix_cache():
    """Sharded serving composes with the PR-9/12 host machinery: a pool
    sized BELOW full residency (preemptions + re-prefill resume) and the
    CoW prefix cache (warm hits on shared prefixes) — block ids are
    replicated host metadata, so both engines make identical decisions
    and the tp=2 outputs stay token-identical through it all."""
    model = make_model(_cfg())
    params = jax.device_get(model.init(jax.random.PRNGKey(0)))
    rng = np.random.default_rng(9)
    shared = rng.integers(0, 128, size=(17,)).astype(np.int32)
    reqs = []
    for i in range(4):
        tail = rng.integers(0, 128, size=(8 + i,)).astype(np.int32)
        # 40 new tokens against an 8-usable-block pool: two tenants'
        # growth crosses the 4-block mark together and the newest
        # preempts (re-prefill resume, then a warm re-admission)
        reqs.append((np.concatenate([shared, tail]), 40))
    serving = dict(max_seqs=2, num_blocks=9, enable_prefix_cache=True)

    def run(mesh):
        srv = _serving(model, params, mesh=mesh, **serving)
        outs = srv.run(list(reqs))
        return outs, srv.stats()

    outs1, st1 = run(None)
    outs2, st2 = run(_mesh(2, tensor=2))
    # the adversarial machinery actually engaged, identically on both
    for st in (st1, st2):
        assert st["preemptions"] >= 1
        assert st["prefix_hits"] >= 1
    assert st1["preemptions"] == st2["preemptions"]
    assert st1["prefix_hits"] == st2["prefix_hits"]
    for rid in outs1:
        np.testing.assert_array_equal(outs1[rid], outs2[rid],
                                      err_msg=f"request {rid}")


@pytest.mark.slow
def test_tp2_latency_tier_composes_token_identical():
    """Speculative decoding (span verify) + chunked prefill under tp=2:
    the decode_span_paged program runs head-sharded like the quantum
    step, and outputs still match the PLAIN single-chip engine exactly
    (the ISSUE-12 K=0 parity contract, now across meshes)."""
    model = make_model(_cfg())
    params = jax.device_get(model.init(jax.random.PRNGKey(0)))
    reqs = _reqs(seed=5, lens=(7, 33), news=(12, 10))
    plain = _serving(model, params).run(list(reqs))
    srv = _serving(model, params, mesh=_mesh(2, tensor=2),
                   spec_tokens=3, prefill_token_budget=48)
    outs = srv.run(list(reqs))
    st = srv.stats()
    assert st["spec_steps"] >= 1 and st["prefill_chunks"] >= 1
    for rid in plain:
        np.testing.assert_array_equal(plain[rid], outs[rid],
                                      err_msg=f"request {rid}")


@pytest.mark.slow
def test_tp2_drain_resume_continues_byte_identical(tmp_path):
    """tp=2 -> tp=2 drained continuation: outputs merge byte-identically
    with the uninterrupted tp=2 run (the PR-10 drain/resume contract on
    a sharded mesh — the 'continues byte-identically' half of the
    geometry satellite)."""
    model = make_model(_cfg())
    params = jax.device_get(model.init(jax.random.PRNGKey(0)))
    reqs = _reqs(seed=7, lens=(9, 25), news=(12, 10))

    base = _serving(model, params, mesh=_mesh(2, tensor=2)).run(list(reqs))

    srv = _serving(model, params, mesh=_mesh(2, tensor=2))
    for p, n in reqs:
        srv.add_request(p, n)
    srv.step()
    srv.drain(str(tmp_path))

    srv2 = _serving(model, params, mesh=_mesh(2, tensor=2))
    srv2.resume(str(tmp_path))
    outs = {}
    while not srv2.scheduler.done:
        for r in srv2.step():
            outs[r.rid] = r.output
    for rid, expect in base.items():
        np.testing.assert_array_equal(expect, outs[rid],
                                      err_msg=f"request {rid}")
