"""Launcher tests (reference: tests/unit/launcher/test_multinode_runner.py
asserts command construction; launch.py behavior is exercised with real
subprocesses here)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from deepspeed_tpu.launcher.launch import LaunchAgent, build_child_env
from deepspeed_tpu.launcher.runner import (build_ssh_commands, fetch_hostfile,
                                           parse_inclusion_exclusion)

WORLD = {"coordinator": "10.0.0.1:1234", "num_nodes": 4}


class TestLaunchAgent:
    def test_env_wiring_standalone(self):
        env = build_child_env(WORLD, 2, base_env={})
        # the names comm.init_distributed actually reads
        assert env["COORDINATOR_ADDRESS"] == "10.0.0.1:1234"
        assert env["NUM_PROCESSES"] == "4"
        assert env["PROCESS_ID"] == "2"
        # torch-style aliases
        assert env["RANK"] == "2" and env["WORLD_SIZE"] == "4"
        assert env["MASTER_ADDR"] == "10.0.0.1"
        assert env["MASTER_PORT"] == "1234"

    def test_env_passthrough_from_runner(self):
        # the runner's env prefix is the source of truth: no world_info
        base = {"COORDINATOR_ADDRESS": "h:9", "NUM_PROCESSES": "2",
                "PROCESS_ID": "1"}
        env = build_child_env(base_env=base)
        assert env["COORDINATOR_ADDRESS"] == "h:9"
        assert env["RANK"] == "1" and env["WORLD_SIZE"] == "2"

    def test_bad_world_info_is_argument_error(self):
        from deepspeed_tpu.launcher.launch import _parse_world_info
        import argparse
        with pytest.raises(argparse.ArgumentTypeError, match="world_info"):
            _parse_world_info("coordinator=h:8476")

    def test_child_sees_env_and_rc_passthrough(self, tmp_path):
        script = tmp_path / "child.py"
        script.write_text(
            "import os, sys, json\n"
            "print(json.dumps({k: os.environ[k] for k in "
            "('PROCESS_ID', 'NUM_PROCESSES')}))\n"
            "sys.exit(7)\n")
        out = tmp_path / "out.txt"
        agent = LaunchAgent(
            [sys.executable, str(script)], WORLD, 1)
        # capture stdout via redirection child-side is overkill; re-spawn
        # through the agent and read rc only, then verify env separately
        rc = agent.run()
        assert rc == 7
        env = build_child_env(WORLD, 1)
        got = subprocess.run([sys.executable, str(script)], env=env,
                             capture_output=True, text=True)
        assert json.loads(got.stdout) == {"PROCESS_ID": "1",
                                          "NUM_PROCESSES": "4"}

    def test_signal_kills_process_group(self, tmp_path):
        """A SIGTERM to the agent tears down a child that spawns its own
        subprocess AND ignores SIGTERM (the kill-escalation path,
        reference launch.py:103)."""
        script = tmp_path / "stubborn.py"
        script.write_text(
            "import signal, subprocess, sys, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "subprocess.Popen([sys.executable, '-c', "
            "'import time; time.sleep(60)'])\n"
            "time.sleep(60)\n")
        agent = LaunchAgent([sys.executable, str(script)], WORLD, 0,
                            kill_grace_s=0.5)
        t0 = time.time()

        def fire():
            time.sleep(0.8)  # let the child start
            agent._forward_signal(signal.SIGTERM, None)

        threading.Thread(target=fire, daemon=True).start()
        rc = agent.run()
        assert time.time() - t0 < 20
        assert rc != 0  # killed, not a clean exit


class TestRunnerCommands:
    def test_hostfile_and_ssh_cmds(self, tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("hostA slots=4\nhostB slots=4\n# comment\n")
        hosts = fetch_hostfile(str(hf))
        assert hosts == {"hostA": 4, "hostB": 4}
        hosts = parse_inclusion_exclusion(hosts, include="", exclude="hostB")
        assert list(hosts) == ["hostA"]
        cmds = build_ssh_commands({"hostA": 4, "hostB": 4},
                                  ["python", "train.py"])
        assert len(cmds) == 2
        assert cmds[0][0] == "ssh" and "hostA" in cmds[0]
        # the remote command routes through the per-node launch agent,
        # with the rendezvous carried ONLY by the env prefix
        assert "deepspeed_tpu.launcher.launch" in cmds[0][-1]
        assert "PROCESS_ID=1" in cmds[1][-1]
        assert "world_info" not in cmds[0][-1]
        raw = build_ssh_commands({"hostA": 4}, ["python", "t.py"],
                                 use_agent=False)
        assert "launcher.launch" not in raw[0][-1]
