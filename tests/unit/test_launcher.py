"""Launcher tests (reference: tests/unit/launcher/test_multinode_runner.py
asserts command construction; launch.py behavior is exercised with real
subprocesses here)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from deepspeed_tpu.launcher.launch import LaunchAgent, build_child_env
from deepspeed_tpu.launcher.runner import (build_ssh_commands, fetch_hostfile,
                                           parse_inclusion_exclusion)

WORLD = {"coordinator": "10.0.0.1:1234", "num_nodes": 4}


class TestLaunchAgent:
    def test_env_wiring_standalone(self):
        env = build_child_env(WORLD, 2, base_env={})
        # the names comm.init_distributed actually reads
        assert env["COORDINATOR_ADDRESS"] == "10.0.0.1:1234"
        assert env["NUM_PROCESSES"] == "4"
        assert env["PROCESS_ID"] == "2"
        # torch-style aliases
        assert env["RANK"] == "2" and env["WORLD_SIZE"] == "4"
        assert env["MASTER_ADDR"] == "10.0.0.1"
        assert env["MASTER_PORT"] == "1234"

    def test_env_passthrough_from_runner(self):
        # the runner's env prefix is the source of truth: no world_info
        base = {"COORDINATOR_ADDRESS": "h:9", "NUM_PROCESSES": "2",
                "PROCESS_ID": "1"}
        env = build_child_env(base_env=base)
        assert env["COORDINATOR_ADDRESS"] == "h:9"
        assert env["RANK"] == "1" and env["WORLD_SIZE"] == "2"

    def test_bad_world_info_is_argument_error(self):
        from deepspeed_tpu.launcher.launch import _parse_world_info
        import argparse
        with pytest.raises(argparse.ArgumentTypeError, match="world_info"):
            _parse_world_info("coordinator=h:8476")

    @pytest.mark.slow
    def test_child_sees_env_and_rc_passthrough(self, tmp_path):
        script = tmp_path / "child.py"
        script.write_text(
            "import os, sys, json\n"
            "print(json.dumps({k: os.environ[k] for k in "
            "('PROCESS_ID', 'NUM_PROCESSES')}))\n"
            "sys.exit(7)\n")
        out = tmp_path / "out.txt"
        agent = LaunchAgent(
            [sys.executable, str(script)], WORLD, 1)
        # capture stdout via redirection child-side is overkill; re-spawn
        # through the agent and read rc only, then verify env separately
        rc = agent.run()
        assert rc == 7
        env = build_child_env(WORLD, 1)
        got = subprocess.run([sys.executable, str(script)], env=env,
                             capture_output=True, text=True)
        assert json.loads(got.stdout) == {"PROCESS_ID": "1",
                                          "NUM_PROCESSES": "4"}

    def test_signal_kills_process_group(self, tmp_path):
        """A SIGTERM to the agent tears down a child that spawns its own
        subprocess AND ignores SIGTERM (the kill-escalation path,
        reference launch.py:103)."""
        script = tmp_path / "stubborn.py"
        script.write_text(
            "import signal, subprocess, sys, time\n"
            "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
            "subprocess.Popen([sys.executable, '-c', "
            "'import time; time.sleep(60)'])\n"
            "time.sleep(60)\n")
        agent = LaunchAgent([sys.executable, str(script)], WORLD, 0,
                            kill_grace_s=0.5)
        t0 = time.time()

        def fire():
            time.sleep(0.8)  # let the child start
            agent._forward_signal(signal.SIGTERM, None)

        threading.Thread(target=fire, daemon=True).start()
        rc = agent.run()
        assert time.time() - t0 < 20
        assert rc != 0  # killed, not a clean exit


class TestRunnerCommands:
    def test_hostfile_and_ssh_cmds(self, tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("hostA slots=4\nhostB slots=4\n# comment\n")
        hosts = fetch_hostfile(str(hf))
        assert hosts == {"hostA": 4, "hostB": 4}
        hosts = parse_inclusion_exclusion(hosts, include="", exclude="hostB")
        assert list(hosts) == ["hostA"]
        cmds = build_ssh_commands({"hostA": 4, "hostB": 4},
                                  ["python", "train.py"])
        assert len(cmds) == 2
        assert cmds[0][0] == "ssh" and "hostA" in cmds[0]
        # the remote command routes through the per-node launch agent,
        # with the rendezvous carried ONLY by the env prefix
        assert "deepspeed_tpu.launcher.launch" in cmds[0][-1]
        assert "PROCESS_ID=1" in cmds[1][-1]
        assert "world_info" not in cmds[0][-1]
        raw = build_ssh_commands({"hostA": 4}, ["python", "t.py"],
                                 use_agent=False)
        assert "launcher.launch" not in raw[0][-1]


class TestMultinodeRunners:
    """Command construction parity (reference:
    tests/unit/launcher/test_multinode_runner.py asserts pdsh/mpirun
    command lines)."""

    HOSTS = {"worker-0": 4, "worker-1": 4}

    def _runner(self, name):
        from deepspeed_tpu.launcher.multinode_runner import get_runner
        return get_runner(name, self.HOSTS, ["python", "train.py", "--x"],
                          master_addr="worker-0", master_port=29501,
                          env={"JAX_PLATFORMS": "tpu", "HOME": "/root",
                               "XLA_FLAGS": "--a --b"},
                          extra_env={"HF_TOKEN": "tok"})

    def test_pdsh_cmd(self):
        import base64
        import json
        cmd = self._runner("pdsh").get_cmd()
        assert cmd[:5] == ["pdsh", "-S", "-f", "1024", "-w"]
        assert cmd[5] == "worker-0,worker-1"
        agent = cmd[6]
        assert "export JAX_PLATFORMS=tpu;" in agent
        # values with spaces are shell-quoted
        assert "export XLA_FLAGS='--a --b';" in agent
        assert "HOME" not in agent           # only whitelisted envs export
        assert "export HF_TOKEN=tok;" in agent  # .deepspeed_env bypasses
        assert "--node_host %h" in agent
        assert agent.endswith("python train.py --x")
        # the world_info payload decodes and carries the host list for the
        # %h -> node-rank resolution done by launch.py
        winfo_b64 = agent.split("--world_info ")[1].split()[0]
        winfo = json.loads(base64.urlsafe_b64decode(winfo_b64))
        assert winfo == {"coordinator": "worker-0:29501", "num_nodes": 2,
                         "hosts": ["worker-0", "worker-1"]}

    def test_pdsh_agent_roundtrips_through_launch_parser(self):
        """The command pdsh sends must parse in launch.py and resolve the
        per-host node rank (the review-found breakage: flags that do not
        exist there)."""
        import shlex as _shlex
        from deepspeed_tpu.launcher import launch as launch_mod
        agent = self._runner("pdsh").get_cmd()[6].replace("%h", "worker-1")
        argv = _shlex.split(agent.split("; ")[-1])[3:]  # after `python -m mod`
        # parse exactly what launch.main would see
        captured = {}

        class FakeAgent:
            def __init__(self, cmd, world, node_rank, **kw):
                captured.update(cmd=cmd, world=world, node_rank=node_rank)
            env = {}
            def run(self):
                return 0

        orig = launch_mod.LaunchAgent
        launch_mod.LaunchAgent = FakeAgent
        try:
            rc = launch_mod.main(argv)
        finally:
            launch_mod.LaunchAgent = orig
        assert rc == 0
        assert captured["node_rank"] == 1
        assert captured["cmd"] == ["python", "train.py", "--x"]
        assert captured["world"]["coordinator"] == "worker-0:29501"

    def test_openmpi_cmd(self):
        cmd = self._runner("openmpi").get_cmd()
        assert cmd[0] == "mpirun"
        # ONE process per host (a jax client drives all local chips);
        # hostfile slots document chip counts, not process counts
        assert cmd[cmd.index("-n") + 1] == "2"
        assert "worker-0:1,worker-1:1" in cmd
        assert "ppr:1:node" in cmd
        assert "JAX_PLATFORMS=tpu" in cmd
        assert "MASTER_ADDR=worker-0" in cmd
        assert cmd[-3:] == ["python", "train.py", "--x"]

    def test_mpich_cmd(self):
        cmd = self._runner("mpich").get_cmd()
        assert cmd[0] == "mpirun"
        assert cmd[cmd.index("-ppn") + 1] == "1"
        i = cmd.index("MASTER_PORT")
        assert cmd[i + 1] == "29501"

    def test_mvapich_adds_env_knobs(self):
        cmd = self._runner("mvapich").get_cmd()
        assert "MV2_SMP_USE_CMA" in cmd

    def test_slurm_cmd(self):
        cmd = self._runner("slurm").get_cmd()
        assert cmd[0] == "srun"
        assert cmd[cmd.index("-n") + 1] == "2"
        assert cmd[cmd.index("--ntasks-per-node") + 1] == "1"
        exp = cmd[cmd.index("--export") + 1]
        assert exp.startswith("ALL,") and "MASTER_ADDR=worker-0" in exp
        # srun --export splits on commas: space/comma values must be dropped
        assert "XLA_FLAGS" not in exp
        assert "HF_TOKEN=tok" in exp

    def test_unknown_launcher_raises(self):
        import pytest as _pytest
        with _pytest.raises(ValueError, match="unknown launcher"):
            self._runner("bogus")

    def test_dstpu_cli_dry_run(self, tmp_path, capsys):
        from deepspeed_tpu.launcher.runner import main
        hf = tmp_path / "hostfile"
        hf.write_text("worker-0 slots=4\nworker-1 slots=4\n")
        rc = main(["--hostfile", str(hf), "--launcher", "slurm",
                   "--dry_run", "train.py"])
        out = capsys.readouterr().out
        assert rc == 0 and out.startswith("srun")


def test_dstpu_ssh_dry_run(tmp_path, capsys):
    from deepspeed_tpu.launcher.runner import ssh_main
    hf = tmp_path / "hostfile"
    hf.write_text("a slots=1\nb slots=1\n")
    rc = ssh_main(["--hostfile", str(hf), "--dry_run", "echo", "hi"])
    out = capsys.readouterr().out.splitlines()
    assert rc == 0 and len(out) == 2 and all("echo hi" in l for l in out)


def test_aio_bench_sweep(tmp_path):
    from deepspeed_tpu.ops.aio import aio_available
    if not aio_available():
        import pytest as _pytest
        _pytest.skip("native aio unavailable")
    from deepspeed_tpu.ops.aio_bench import sweep
    # buffered IO: the CI tmpdir may not support O_DIRECT; the sweep
    # MACHINERY is under test here, not the device
    rows = sweep(str(tmp_path), file_mb=2, iters=1,
                 block_sizes=[1 << 20], queue_depths=[4, 16],
                 thread_counts=[2], direct=False)
    assert len(rows) == 2
    assert all(r.get("read_gbps", 0) > 0 for r in rows), rows
