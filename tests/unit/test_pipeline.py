"""Pipeline parallelism tests (reference: tests/unit/runtime/pipe — schedule
correctness vs DDP parity, pipe module partitioning)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.parallel.pipeline import bubble_fraction
from tests.conftest import make_batch

# quick tier: `pytest -m 'not slow'` skips this module (1F1B shard_map programs are compile-heavy)
pytestmark = pytest.mark.slow


def tiny_cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla")
    base.update(kw)
    return TransformerConfig(**base)


def ds_cfg(**overrides):
    cfg = {
        "train_batch_size": 32,
        "gradient_accumulation_steps": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "steps_per_print": 1000,
    }
    cfg.update(overrides)
    return cfg


def run(config, steps=5, seed=0):
    model = make_model(tiny_cfg())
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
    batch = make_batch(32, 32, vocab=64, seed=seed)
    return [float(engine.train_batch(batch)["loss"]) for _ in range(steps)], engine


class TestPipelineParity:
    def test_pp2_matches_dp(self):
        """PP=2 over 4 layers must produce the same training curve as pure DP
        (the reference asserts pipe-vs-DDP parity the same way)."""
        base, _ = run(ds_cfg())
        pp, engine = run(ds_cfg(pipeline={"stages": 2}))
        np.testing.assert_allclose(base, pp, rtol=2e-4, atol=1e-5)
        # layers must actually shard over pipe
        wq = engine.state["params"]["layers"]["wq"]
        flat = [a for s in wq.sharding.spec if s is not None
                for a in (s if isinstance(s, tuple) else (s,))]
        assert "pipe" in flat

    def test_pp4_matches_dp(self):
        base, _ = run(ds_cfg())
        pp, _ = run(ds_cfg(pipeline={"stages": 4}))
        np.testing.assert_allclose(base, pp, rtol=2e-4, atol=1e-5)

    def test_pp2_with_zero1(self):
        pp, _ = run(ds_cfg(pipeline={"stages": 2},
                           zero_optimization={"stage": 1}))
        assert pp[-1] < pp[0]

    def test_pp2_with_tp2(self):
        """3D: pipe=2 x tensor=2 x data=2 on 8 devices."""
        pp, _ = run(ds_cfg(pipeline={"stages": 2},
                           tensor_parallel={"size": 2}))
        assert pp[-1] < pp[0]

    def test_indivisible_layers_raises(self):
        model = make_model(tiny_cfg(num_layers=3))
        with pytest.raises(ValueError, match="divisible"):
            deepspeed_tpu.initialize(model=model,
                                     config=ds_cfg(pipeline={"stages": 2}))


class Test1F1B:
    """The interleaved fwd/bwd schedule (reference: runtime/pipe/schedule.py
    TrainSchedule) — grads must match plain autodiff of the unpipelined
    loss, and the lifted restrictions (mask, dropout) must work."""

    def test_grads_match_unpipelined(self, devices8):
        from deepspeed_tpu.models.pipeline_wrapper import make_pipelined_model
        from deepspeed_tpu.models.transformer import init_params, lm_loss
        from deepspeed_tpu.parallel import MeshPlan, build_mesh
        cfg = tiny_cfg()
        mesh = build_mesh(MeshPlan(pipe=4, data=2))
        pmodel = make_pipelined_model(cfg, mesh, num_microbatches=8)
        params = init_params(jax.random.PRNGKey(0), cfg)
        batch = make_batch(16, 32, vocab=64, seed=3)
        batch = {k: jnp.asarray(v) for k, v in batch.items()}

        with mesh:
            loss_pp, grads_pp = jax.jit(jax.value_and_grad(
                lambda p: pmodel.loss_fn(p, batch)))(params)
        # reference: per-microbatch CE means averaged over M (gas semantics)
        def ref_loss(p):
            ids = batch["input_ids"].reshape(8, 2, 32)
            losses = [lm_loss(p, {"input_ids": ids[i]}, cfg) for i in range(8)]
            return sum(losses) / 8
        loss_ref, grads_ref = jax.jit(jax.value_and_grad(ref_loss))(params)
        np.testing.assert_allclose(float(loss_pp), float(loss_ref),
                                   rtol=1e-5, atol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-5),
            grads_pp, grads_ref)

    def test_pp_with_attention_mask(self):
        """Padding masks are supported in pipeline mode now."""
        model = make_model(tiny_cfg())
        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=ds_cfg(pipeline={"stages": 2}))
        b = make_batch(32, 32, vocab=64, seed=1)
        mask = np.ones((32, 32), np.int32)
        mask[:, 24:] = 0
        b["attention_mask"] = mask
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(3)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_pp_with_dropout(self):
        """Dropout inside the pipelined stack is supported now; the 1F1B
        backward recompute must see the same masks (finite, decreasing)."""
        model = make_model(tiny_cfg(dropout_rate=0.1))
        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=ds_cfg(pipeline={"stages": 2}))
        b = make_batch(32, 32, vocab=64, seed=2)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(5)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_pp_bf16_with_tp(self):
        """bf16 grads psum'd over pipe (regression: XLA-CPU bf16 all-reduce
        promotion crash — grads now reduce in f32)."""
        model = make_model(tiny_cfg(dtype=jnp.bfloat16))
        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=ds_cfg(pipeline={"stages": 2},
                                       tensor_parallel={"size": 2},
                                       bf16={"enabled": True}))
        b = make_batch(32, 32, vocab=64, seed=4)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(4)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_live_activation_bound(self, devices8):
        """1F1B memory contract: the compiled train program's live-buffer
        requirement must NOT grow with microbatch count M (GPipe's does)."""
        from deepspeed_tpu.models.pipeline_wrapper import make_pipelined_model
        from deepspeed_tpu.models.transformer import init_params
        from deepspeed_tpu.parallel import MeshPlan, build_mesh
        cfg = tiny_cfg()
        mesh = build_mesh(MeshPlan(pipe=4, data=2))

        def peak_bytes(M):
            pmodel = make_pipelined_model(cfg, mesh, num_microbatches=M)
            params = init_params(jax.random.PRNGKey(0), cfg)
            batch = {"input_ids": jnp.asarray(
                make_batch(2 * M, 32, vocab=64)["input_ids"])}
            with mesh:
                lowered = jax.jit(jax.grad(
                    lambda p: pmodel.loss_fn(p, batch))).lower(params)
                compiled = lowered.compile()
            ma = compiled.memory_analysis()
            if ma is None or not hasattr(ma, "temp_size_in_bytes"):
                pytest.skip("memory_analysis unavailable on this backend")
            return ma.temp_size_in_bytes

        m8, m16 = peak_bytes(8), peak_bytes(16)
        # batch doubles with M (mb held at 2): allow growth for the batch
        # itself but temp must stay well below proportional scaling
        assert m16 < 1.5 * m8, (m8, m16)


def test_bubble_fraction():
    assert bubble_fraction(1, 1) == 0.0
    assert abs(bubble_fraction(4, 2) - 1 / 5) < 1e-9
    assert bubble_fraction(8, 2) < bubble_fraction(4, 2)


def test_pp_with_moe(devices8):
    """MoE layers inside the pipelined stack (reference: PP+MoE support):
    pp=2 must reproduce the pp=1 training curve (aux-loss weighting and the
    1F1B aux vjp seeds included). Batch sized so per-replica micro >= 4:
    smaller hits an XLA-CPU thunk-executor abort in scan-of-MoE (runs fine
    on real TPU)."""
    batch = make_batch(128, 32, vocab=64, seed=9)

    def curve(extra):
        model = make_model(tiny_cfg(num_experts=2, top_k=1))
        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=ds_cfg(train_batch_size=128, **extra))
        return [float(engine.train_batch(batch)["loss"]) for _ in range(5)]

    base = curve({})
    pp = curve({"pipeline": {"stages": 2}})
    np.testing.assert_allclose(base, pp, rtol=5e-4, atol=1e-5)
