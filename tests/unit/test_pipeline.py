"""Pipeline parallelism tests (reference: tests/unit/runtime/pipe — schedule
correctness vs DDP parity, pipe module partitioning)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.parallel.pipeline import bubble_fraction
from tests.conftest import make_batch


def tiny_cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla")
    base.update(kw)
    return TransformerConfig(**base)


def ds_cfg(**overrides):
    cfg = {
        "train_batch_size": 32,
        "gradient_accumulation_steps": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "steps_per_print": 1000,
    }
    cfg.update(overrides)
    return cfg


def run(config, steps=5, seed=0):
    model = make_model(tiny_cfg())
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
    batch = make_batch(32, 32, vocab=64, seed=seed)
    return [float(engine.train_batch(batch)["loss"]) for _ in range(steps)], engine


class TestPipelineParity:
    def test_pp2_matches_dp(self):
        """PP=2 over 4 layers must produce the same training curve as pure DP
        (the reference asserts pipe-vs-DDP parity the same way)."""
        base, _ = run(ds_cfg())
        pp, engine = run(ds_cfg(pipeline={"stages": 2}))
        np.testing.assert_allclose(base, pp, rtol=2e-4, atol=1e-5)
        # layers must actually shard over pipe
        wq = engine.state["params"]["layers"]["wq"]
        flat = [a for s in wq.sharding.spec if s is not None
                for a in (s if isinstance(s, tuple) else (s,))]
        assert "pipe" in flat

    def test_pp4_matches_dp(self):
        base, _ = run(ds_cfg())
        pp, _ = run(ds_cfg(pipeline={"stages": 4}))
        np.testing.assert_allclose(base, pp, rtol=2e-4, atol=1e-5)

    def test_pp2_with_zero1(self):
        pp, _ = run(ds_cfg(pipeline={"stages": 2},
                           zero_optimization={"stage": 1}))
        assert pp[-1] < pp[0]

    def test_pp2_with_tp2(self):
        """3D: pipe=2 x tensor=2 x data=2 on 8 devices."""
        pp, _ = run(ds_cfg(pipeline={"stages": 2},
                           tensor_parallel={"size": 2}))
        assert pp[-1] < pp[0]

    def test_indivisible_layers_raises(self):
        model = make_model(tiny_cfg(num_layers=3))
        with pytest.raises(ValueError, match="divisible"):
            deepspeed_tpu.initialize(model=model,
                                     config=ds_cfg(pipeline={"stages": 2}))


def test_bubble_fraction():
    assert bubble_fraction(1, 1) == 0.0
    assert abs(bubble_fraction(4, 2) - 1 / 5) < 1e-9
    assert bubble_fraction(8, 2) < bubble_fraction(4, 2)
