"""ISSUE-8 perf levers: fused attention backward, chunked TP overlap,
tied-embedding head fix, serialized-backward corpus, comms census summary.

Pins the tentpole contracts:
  * the tied-embedding lm_head (lm_head_logits: dot_general on the
    UNtransposed table + the forward-only vocab constraint) compiles on an
    fsdp x tensor mesh with ZERO involuntary-remat findings — the r5
    MULTICHIP DIAGNOSIS turned into a regression floor;
  * `ops.flash_attention(fused_backward=True)` (delta epilogue inside the
    backward Pallas grids) is BIT-FOR-BIT identical to the unfused path —
    kernel-level, and end-to-end over 20 fp16 engine steps with a forced
    overflow across ZeRO stages 1/3 (test_comm_schedule methodology);
  * `parallel.partitioning.row_parallel_matmul` (chunked collective-matmul
    overlap) is bit-identical to the plain matmul on a tensor mesh, falls
    back cleanly off-mesh, and the engine-level `transformer.
    tp_overlap_chunks` path trains bit-for-bit vs the unchunked path;
  * the `dots_and_attn` remat policy saves the flash kernel's named
    outputs across the fwd/bwd boundary — the backward stops replaying the
    online-softmax forward (pallas_call count drops);
  * corpus `serialized-backward` fires census-drift + collective-exposed
    from `lint --corpus` and exposed-collective-measured from
    `doctor --corpus`, while the correctly-chunked twin passes the census;
  * `comm.log_summary(engine=)` reports the GSPMD census of the real
    compiled train step (kinds + bytes) next to the trace-time totals.

Bit-parity methodology: both fused-backward and chunked-TP REORDER nothing
— the fused grids compute the same f32 delta the XLA pass computed, and
each chunked output element sums the same per-shard partials in the same
order — so parity is exact, not approximate. The forced overflow at step 7
pokes the live loss scale to 2^24: the engine trains the model in fp16, so
scaled grads (~scale x O(1)) blow past fp16's 65504 max and go non-finite
deterministically, then the backoff halves the scale each skipped step
until grads fit again — the run overflows for a deterministic handful of
steps and RECOVERS inside the 20-step window (2^127 never recovers: ~110
halvings needed). Both arms of every comparison get the identical poke, so
the skip/hysteresis path is exercised under parity and the overflow counts
must match exactly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.ops.flash_attention import flash_attention


@pytest.fixture
def devices8():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


def tiny_tied(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla")
    base.update(kw)
    return make_model(TransformerConfig(**base), name="levers-tiny")


def engine_cfg(stage, axes, **overrides):
    cfg = {"train_batch_size": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "fp16": {"enabled": True, "initial_scale_power": 8},
           "bf16": {"enabled": False},
           "zero_optimization": {"stage": stage,
                                 "stage3_param_persistence_threshold": 0},
           "mesh": {"axes": axes},
           "steps_per_print": 100}
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k] = {**cfg[k], **v}
        else:
            cfg[k] = v
    return cfg


def token_batches(n=20, vocab=64, rows=4, seq=32):
    rng = np.random.default_rng(0)
    return [{"input_ids": rng.integers(0, vocab, size=(rows, seq),
                                       dtype=np.int32)}
            for _ in range(n)]


def force_overflow(engine):
    """Poke the live loss scale to 2^24: the fp16 model's scaled grads
    (~scale x O(1) > 65504) go non-finite, the overflow/skip path runs and
    the backoff halves the scale until grads fit fp16 again — a
    deterministic overflow burst that recovers within the step budget."""
    leaf = engine.state["loss_scale"]["scale"]
    engine.state["loss_scale"]["scale"] = jax.device_put(
        jnp.float32(2.0 ** 24), leaf.sharding)


def run_parity(model_fn, cfg_a, cfg_b, n=20, boost_at=7, devices=None):
    """Train two engines over the same batches with a forced overflow at
    `boost_at`; return (params_a, params_b, overflows_a, overflows_b)."""
    outs = []
    for cfg in (cfg_a, cfg_b):
        engine, *_ = deepspeed_tpu.initialize(
            model=model_fn(), config=cfg,
            devices=devices or list(jax.devices()))
        overflows = 0
        for i, b in enumerate(token_batches(n)):
            if i == boost_at:
                force_overflow(engine)
            m = engine.train_batch(b)
            overflows += int(bool(np.asarray(jax.device_get(m["overflow"]))))
        params = jax.device_get(engine.state["params"])
        outs.append((params, overflows))
        del engine
    (pa, oa), (pb, ob) = outs
    return pa, pb, oa, ob


def assert_params_bitwise(pa, pb):
    la, lb = jax.tree.leaves(pa), jax.tree.leaves(pb)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# --------------------------------------------------------------------------
# tied-embedding head on fsdp x tensor meshes (the r5 DIAGNOSIS, fixed)
# --------------------------------------------------------------------------

class TestTiedEmbeddingRemat:
    def test_fsdp_x_tensor_compiles_without_involuntary_remat(self, devices8):
        """The regression floor for the r5 MULTICHIP DIAGNOSIS: the tied
        model under stage-3 on a 2-axis mesh must show ZERO
        involuntary-remat findings from RematAudit (the transpose at the
        old lm_head fallback forced a full per-step rematerialization)."""
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_tied(),
            config={"train_batch_size": 4,
                    "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "bf16": {"enabled": False},
                    "zero_optimization": {
                        "stage": 3, "stage3_param_persistence_threshold": 0},
                    "mesh": {"axes": {"fsdp": 2, "tensor": 2}},
                    "steps_per_print": 100},
            devices=devices8[:4])
        report = engine.audit(
            batch={"input_ids": np.zeros((4, 16), np.int32)})
        remat = [f for f in report.findings if f.rule == "involuntary-remat"]
        assert not remat, "\n".join(f.message for f in remat)

    def test_tied_vs_untied_logits_match(self):
        """lm_head_logits contracts the UNtransposed table; numerically it
        must equal the explicit-transpose head it replaced."""
        from deepspeed_tpu.models.transformer import lm_head_logits
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
        table = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
        tied = lm_head_logits(x, {"tok_embed": table})
        untied = lm_head_logits(x, {"lm_head": table.T})
        np.testing.assert_array_equal(np.asarray(tied), np.asarray(untied))


# --------------------------------------------------------------------------
# fused attention backward (kernel level, interpret mode)
# --------------------------------------------------------------------------

class TestFusedBackwardKernel:
    def test_fused_bitwise_equals_unfused(self):
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        B, S, N, D = 1, 256, 2, 64
        q = jax.random.normal(ks[0], (B, S, N, D), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, N, D), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, N, D), jnp.float32)
        do = jax.random.normal(ks[3], (B, S, N, D), jnp.float32)

        def grads(fused):
            f = lambda q, k, v: jnp.sum(
                flash_attention(q, k, v, causal=True, fused_backward=fused)
                * do)
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        g0, g1 = grads(False), grads(True)
        for a, b in zip(g0, g1):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    def test_dots_and_attn_policy_skips_flash_replay(self):
        """Under layer-level jax.checkpoint, dot-only policies recompute
        the flash custom-vjp outputs — the backward replays the full
        online-softmax forward kernel. dots_and_attn pins the kernel's
        named outputs (flash_out/flash_lse) across the boundary: the
        backward jaxpr holds one FEWER pallas_call."""
        from deepspeed_tpu.models.transformer import _remat_policy

        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (1, 128, 2, 64), jnp.float32)
        k = jax.random.normal(ks[1], (1, 128, 2, 64), jnp.float32)
        v = jax.random.normal(ks[2], (1, 128, 2, 64), jnp.float32)

        def counts(policy_name):
            cfg = TransformerConfig(vocab_size=8, hidden_size=128,
                                    num_layers=1, num_heads=2,
                                    remat=True, remat_policy=policy_name)
            fn = jax.checkpoint(
                lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v, causal=True)),
                policy=_remat_policy(cfg))
            jaxpr = jax.make_jaxpr(jax.grad(fn, argnums=(0, 1, 2)))(q, k, v)
            return str(jaxpr).count("pallas_call")

        saveable = counts("dots_saveable")
        pinned = counts("dots_and_attn")
        assert pinned == saveable - 1, (saveable, pinned)


# --------------------------------------------------------------------------
# chunked TP collective-matmul overlap
# --------------------------------------------------------------------------

class TestRowParallelMatmul:
    def test_bitwise_on_tensor_mesh(self, devices8):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deepspeed_tpu.parallel.partitioning import row_parallel_matmul
        mesh = Mesh(np.array(devices8[:2]), ("tensor",))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((2, 64, 32)), jnp.float32)
        w = jax.device_put(
            jnp.asarray(rng.standard_normal((32, 16)), jnp.float32),
            NamedSharding(mesh, P("tensor", None)))
        with mesh:
            plain = jax.jit(lambda x, w: x @ w)(x, w)
            chunked = jax.jit(
                lambda x, w: row_parallel_matmul(x, w, chunks=4))(x, w)
        assert np.asarray(plain).tobytes() == np.asarray(chunked).tobytes()

    def test_fallback_without_mesh(self):
        from deepspeed_tpu.parallel.partitioning import row_parallel_matmul
        x = jnp.ones((2, 8, 4), jnp.float32)
        w = jnp.ones((4, 4), jnp.float32)
        out = row_parallel_matmul(x, w, chunks=4)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x @ w))

    def test_chunk_census_on_tensor_mesh(self, devices8):
        """The chunked decomposition compiles to `chunks` independent
        all-reduces (the serialized twin compiles to ONE) — the census
        shape the serialized-backward corpus entry pins."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from deepspeed_tpu.analysis.hlo_parse import (collective_census,
                                                      parse_overlap)
        from deepspeed_tpu.parallel.partitioning import row_parallel_matmul
        mesh = Mesh(np.array(devices8[:2]), ("tensor",))
        x_abs = jax.ShapeDtypeStruct((8, 256, 128), jnp.float32)
        w_abs = jax.ShapeDtypeStruct(
            (128, 64), jnp.float32,
            sharding=NamedSharding(mesh, P("tensor", None)))

        def census_of(fn):
            with mesh:
                compiled = jax.jit(fn).lower(x_abs, w_abs).compile()
            return collective_census(parse_overlap(compiled.as_text()))

        serial = census_of(lambda x, w: x @ w)
        chunked = census_of(
            lambda x, w: row_parallel_matmul(x, w, chunks=4))
        assert serial.get("all-reduce", {}).get("count") == 1, serial
        assert chunked.get("all-reduce", {}).get("count") == 4, chunked


# --------------------------------------------------------------------------
# engine-level bit-for-bit parity (20 fp16 steps, forced overflow)
# --------------------------------------------------------------------------

class TestEngineParity:
    """Numerics-parity cases: 2 engine builds x 20 fp16 steps each — slow
    tier (tests/run_slow.sh `perf_levers` budget line); the kernel-level
    bitwise pins above stay quick."""

    @pytest.mark.slow
    @pytest.mark.parametrize("stage", [1, 3])
    def test_tp_overlap_on_off_bitwise(self, stage, devices8):
        """transformer.tp_overlap_chunks on/off across ZeRO 1/3 on a
        data=2 x tensor=2 mesh: 20 fp16 steps, forced overflow at 7."""
        axes = {"data": 2, "tensor": 2}
        base = engine_cfg(stage, axes)
        chunked = engine_cfg(stage, axes,
                             transformer={"tp_overlap_chunks": 4})
        pa, pb, oa, ob = run_parity(tiny_tied, base, chunked,
                                    devices=list(devices8)[:4])
        # both arms overflow for the same deterministic burst AND recover
        # (strictly fewer skips than the 13 post-poke steps)
        assert oa == ob and 1 <= oa <= 12, (oa, ob)
        assert_params_bitwise(pa, pb)

    @pytest.mark.slow
    @pytest.mark.parametrize("stage", [1, 3])
    def test_fused_backward_on_off_bitwise(self, stage, devices8):
        """transformer.fused_backward on/off across ZeRO 1/3: the flash
        kernel (interpret mode on CPU) with the delta epilogue fused into
        the backward grids vs the separate XLA delta pass. 20 fp16 steps,
        forced overflow at 7, params bit-identical."""
        model_fn = lambda: tiny_tied(attention_impl="pallas",
                                     hidden_size=128, num_heads=2,
                                     max_seq_len=128)
        axes = {"data": 2}
        base = engine_cfg(stage, axes)
        fused = engine_cfg(stage, axes,
                           transformer={"fused_backward": True})
        pa, pb, oa, ob = run_parity(model_fn, base, fused,
                                    devices=list(devices8)[:2])
        assert oa == ob and 1 <= oa <= 12, (oa, ob)
        assert_params_bitwise(pa, pb)


# --------------------------------------------------------------------------
# engine `transformer` tuning section
# --------------------------------------------------------------------------

class TestTransformerTuningConfig:
    def test_rebuild_applies_levers(self):
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_tied(),
            config=engine_cfg(0, {"data": 1},
                              transformer={"fused_backward": True,
                                           "tp_overlap_chunks": 4}),
            devices=list(jax.devices())[:1])
        assert engine.model.config.fused_backward is True
        assert engine.model.config.tp_overlap_chunks == 4

    def test_non_transformer_model_ignored(self):
        class Lin:
            name = "lin"
            logical_axes = {"w": None}

            def init(self, rng):
                return {"w": jnp.eye(4, dtype=jnp.float32)}

            def loss_fn(self, params, batch, rng, deterministic):
                return jnp.mean((batch["x"] @ params["w"]) ** 2)

        engine, *_ = deepspeed_tpu.initialize(
            model=Lin(),
            config={"train_batch_size": 4,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "bf16": {"enabled": False},
                    "transformer": {"fused_backward": True},
                    "steps_per_print": 100},
            devices=list(jax.devices())[:1])
        m = engine.train_batch({"x": np.ones((4, 4), np.float32)})
        assert np.isfinite(float(np.asarray(jax.device_get(m["loss"]))))


# --------------------------------------------------------------------------
# serialized-backward corpus (lint + doctor faces)
# --------------------------------------------------------------------------

class TestSerializedBackwardCorpus:
    def test_lint_entry_fires_census_and_exposure(self, devices8):
        from deepspeed_tpu.analysis.corpus import run_corpus
        report = run_corpus("serialized-backward", devices=devices8[:2])
        assert not report.ok
        rules = {f.rule for f in report.findings}
        assert "collective-census-drift" in rules, rules
        assert "collective-exposed" in rules, rules

    def test_doctor_entry_fires_measured_gate(self):
        from deepspeed_tpu.profiling.doctor import run_corpus_entry
        report = run_corpus_entry("serialized-backward")
        assert not report.ok
        assert any(f.rule == "exposed-collective-measured"
                   for f in report.findings)

    def test_doctor_cli_exits_nonzero(self):
        from deepspeed_tpu.profiling import doctor
        assert doctor.main(["--corpus", "serialized-backward"]) != 0


# --------------------------------------------------------------------------
# comms logger census summary
# --------------------------------------------------------------------------

class _Monitor:
    enabled = True

    def __init__(self):
        self.events = []

    def write_events(self, evs):
        self.events.extend(evs)


class TestLogSummaryCensus:
    def test_gspmd_census_in_summary_and_events(self, devices8):
        from deepspeed_tpu.comm import comm as dscomm
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_tied(),
            config={"train_batch_size": 4,
                    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                    "bf16": {"enabled": False},
                    "zero_optimization": {"stage": 2},
                    "mesh": {"axes": {"data": 2}},
                    "telemetry": {"enabled": True},
                    "steps_per_print": 100},
            devices=devices8[:2])
        engine.train_batch({"input_ids": np.zeros((4, 16), np.int32)})
        mon = _Monitor()
        msg = dscomm.log_summary(monitor=mon, step=1, engine=engine)
        # the real stage-2 train step HAS GSPMD collectives; the summary
        # must name kinds + megabytes the trace-time record never saw
        assert "gspmd census (compiled train step)" in msg
        assert "gspmd/all-reduce" in msg or "gspmd/reduce-scatter" in msg
        names = {n for n, _, _ in mon.events}
        assert any(n.startswith("comm/gspmd/") and n.endswith("/bytes")
                   for n in names), names
