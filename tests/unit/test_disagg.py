"""Disaggregated prefill/decode serving (ISSUE 19): KV-byte handoff +
role-aware routing + the autoscaling fleet controller.

The load-bearing contracts pinned here:

  - ``export_kv``/``accept_migration(kv=)`` hands a prefill-done request
    across engines by SHIPPING THE POOL BYTES (one gather + one scatter)
    and the continuation is TOKEN-IDENTICAL to the colocated engine —
    f32 exact, int8-KV exact too (quantized blocks + scales travel
    together, so the receiver's pool state is bit-equal);
  - any payload the receiver cannot scatter bit-faithfully (geometry /
    kv-bits / torn checksum) refuses with the typed
    ``ResumeIncompatible`` BEFORE anything is enqueued, and the ordinary
    re-prefill migration (the path old drain records take) still lands
    the continuation token-identically;
  - a ``role="prefill"`` engine never decodes; the router routes new
    requests to prefill-capable replicas, sweeps prefill-done work onto
    the decode tier, and old no-role heartbeats interop as "both";
  - the ``kv_handoff`` fault seam (fail / corrupt) degrades to
    re-prefill — a torn payload is caught by the crc, never decoded;
  - the FleetController scales the tier up under sustained SLO pressure
    and drains it on lull through ``decommission`` (integrity-chain
    drain + failover + heartbeat retirement) with ZERO lost requests.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference.fleet import FleetConfig, FleetController
from deepspeed_tpu.inference.kv_cache import kv_payload_nbytes
from deepspeed_tpu.inference.router import (ReplicaHandle, RouterConfig,
                                            ServingRouter)
from deepspeed_tpu.inference.scheduler import AdmissionRejected
from deepspeed_tpu.inference.serving import (ResumeIncompatible,
                                             kv_payload_crc)
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.faults import FaultInjector, FaultSchedule


@pytest.fixture(autouse=True)
def _clean_robustness_state():
    rb_faults.clear()
    rb_events.clear()
    yield
    rb_faults.clear()
    rb_events.clear()


def _cfg(**overrides):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, max_seq_len=128, position_type="rotary",
                activation="silu_glu", norm_type="rmsnorm",
                tie_embeddings=False, dtype=jnp.float32,
                attention_impl="xla")
    base.update(overrides)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def model():
    return make_model(_cfg())


@pytest.fixture(scope="module")
def params(model):
    return jax.device_get(model.init(jax.random.PRNGKey(0)))


def _serving(model, params, config=None, mesh=None, **kw):
    d = dict(max_seqs=3, block_size=16, max_model_len=128,
             decode_quantum=2, prompt_bucket=16, decode_backend="xla",
             num_blocks=24)
    d.update(kw)
    return deepspeed_tpu.init_serving(model, config=config or {},
                                     serving=d, dtype=jnp.float32,
                                     params=params, mesh=mesh)


def _reqs(seed=0, n=3, lens=(7, 21, 12), news=(8, 6, 9), vocab=128):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, vocab, size=(lens[i % len(lens)],)
                          ).astype(np.int32), news[i % len(news)])
            for i in range(n)]


def _prefill_all(srv, reqs):
    """Admit ``reqs`` and step until every one is prefill-done with its
    first token sampled (the handoff-ready state)."""
    rids = [srv.add_request(p, max_new_tokens=k) for p, k in reqs]
    for _ in range(200):
        srv.step()
        live = {r.rid: r for r in srv.scheduler.running}
        if all(rid in live and live[rid].prefill_done
               and live[rid].generated for rid in rids):
            return rids
    raise AssertionError("prefill never completed on the source engine")


def _run_to_done(srv, rids, budget=400):
    outs = {}
    for _ in range(budget):
        for r in srv.step():
            outs[r.rid] = r.output
        if set(outs) >= set(rids):
            return outs
    raise AssertionError(f"requests {set(rids) - set(outs)} never finished")


# ---------------------------------------------------------------------------
# the KV-byte handoff: token-identical, typed refusals, payload hygiene
# ---------------------------------------------------------------------------

class TestKvHandoff:
    def test_handoff_token_identical_f32(self, model, params):
        """The headline contract: export -> release -> accept(kv=) on a
        second engine continues every request EXACTLY as the colocated
        engine would have — the receiver re-computes only the pending
        token's row (one tail span), not the prompt."""
        reqs = _reqs()
        base = _serving(model, params).run([(p.copy(), k) for p, k in reqs])

        src = _serving(model, params, role="prefill")
        dst = _serving(model, params, role="decode")
        rids = _prefill_all(src, reqs)
        payloads = src.export_kv(rids)
        assert sorted(payloads) == sorted(rids)
        for (p, _), rid in zip(reqs, rids):
            # pending-token protocol: the prefill sampled the first
            # token, so exported rows == full prompt — strictly inside
            # the (prompt + first token) context
            assert payloads[rid]["rows"] == len(p)
        recs = src.release_requests(rids)
        assert src.scheduler.done and not src._requests
        dst.accept_migration(recs, source="src", kv=payloads)
        outs = _run_to_done(dst, rids)
        assert set(outs) == set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged across the handoff")
        # the fast path really ran: no fallback on either side
        assert src.stats()["handoff_fallbacks"] == 0
        assert dst.stats()["handoff_fallbacks"] == 0
        assert dst.stats()["handoffs"] == len(rids)

    def test_payload_schema_staging_and_counters(self, model, params):
        """Payload carries schema/rows/blocks/geometry/crc; the staged
        bytes are priced into ``pool_bytes``/``kv_staging_bytes`` until
        the hop completes; ``reset_stats`` clears the counters."""
        src = _serving(model, params, role="prefill")
        dst = _serving(model, params, role="decode")
        (rid,) = _prefill_all(src, _reqs(n=1))
        pool_before = src.stats()["pool_bytes"]
        payloads = src.export_kv([rid])
        pl = payloads[rid]
        assert pl["schema"] == 1
        assert pl["geometry"]["block_size"] == 16
        assert pl["geometry"]["num_layers"] == 2
        assert pl["geometry"]["kv_bits"] == 0
        assert pl["crc"] == kv_payload_crc(pl["data"])
        nbytes = kv_payload_nbytes(pl["data"])
        assert nbytes > 0
        st = src.stats()
        assert st["kv_staging_bytes"] == nbytes
        assert st["pool_bytes"] == pool_before + nbytes
        assert st["handoffs"] == 1 and st["handoff_bytes"] == nbytes
        recs = src.release_requests([rid])
        assert src.stats()["kv_staging_bytes"] == 0   # hop consumed it
        dst.accept_migration(recs, source="src", kv=payloads)
        assert dst.stats()["kv_staging_bytes"] == nbytes
        _run_to_done(dst, [rid])
        st = dst.stats()
        assert st["kv_staging_bytes"] == 0            # scatter consumed it
        assert st["handoffs"] == 1 and st["handoff_bytes"] == nbytes
        dst.reset_stats()
        st = dst.stats()
        assert st["handoffs"] == 0 and st["handoff_bytes"] == 0
        assert st["handoff_fallbacks"] == 0

    def test_export_skips_requests_without_rows(self, model, params):
        """A request with nothing cached (still waiting) or an unknown
        rid exports nothing — the caller's fallback is the ordinary
        re-prefill migration, never a malformed payload."""
        src = _serving(model, params)
        rid = src.add_request(np.arange(9, dtype=np.int32),
                              max_new_tokens=4)
        assert src.export_kv([rid, 777]) == {}   # no step yet: no rows

    def test_geometry_mismatch_refuses_typed_then_fallback(
            self, model, params):
        """A block-size-mismatched payload refuses with the typed
        ``ResumeIncompatible`` BEFORE anything is enqueued
        (all-or-nothing), and the same records land token-identically
        through the re-prefill path — old drain records keep working."""
        reqs = _reqs(n=2)
        base = _serving(model, params).run([(p.copy(), k) for p, k in reqs])
        src = _serving(model, params, role="prefill")
        dst = _serving(model, params, block_size=8, num_blocks=48)
        rids = _prefill_all(src, reqs)
        payloads = src.export_kv(rids)
        recs = src.release_requests(rids)
        with pytest.raises(ResumeIncompatible, match="block_size"):
            dst.accept_migration(recs, source="src", kv=payloads)
        assert not dst._requests                 # nothing half-landed
        assert dst.stats()["handoff_fallbacks"] >= 1
        dst.accept_migration(recs, source="src")  # the re-prefill path
        outs = _run_to_done(dst, rids)
        for rid in base:
            np.testing.assert_array_equal(base[rid], outs[rid])

    def test_torn_payload_refused_by_checksum(self, model, params):
        """Size-preserving bitrot in the payload buffers fails the crc —
        typed refusal, then the fallback serves the exact tokens. The
        receiver must never scatter (and decode from) garbage."""
        reqs = _reqs(n=1)
        base = _serving(model, params).run([(p.copy(), k) for p, k in reqs])
        src = _serving(model, params, role="prefill")
        dst = _serving(model, params, role="decode")
        rids = _prefill_all(src, reqs)
        payloads = src.export_kv(rids)
        flat = payloads[rids[0]]["data"]["k"].reshape(-1).view(np.uint8)
        flat[: max(1, flat.size // 16)] ^= 0xFF
        recs = src.release_requests(rids)
        with pytest.raises(ResumeIncompatible, match="checksum"):
            dst.accept_migration(recs, source="src", kv=payloads)
        dst.accept_migration(recs, source="src")
        outs = _run_to_done(dst, rids)
        np.testing.assert_array_equal(base[rids[0]], outs[rids[0]])

    def test_rows_outside_pending_token_protocol_refused(
            self, model, params):
        """rows must sit strictly inside (0, ctx): the receiver's tail
        span computes the row AT cached_rows, so a full-context payload
        is as malformed as an empty one."""
        src = _serving(model, params, role="prefill")
        dst = _serving(model, params, role="decode")
        (rid,) = _prefill_all(src, _reqs(n=1))
        payloads = src.export_kv([rid])
        recs = src.release_requests([rid])
        ctx = len(recs[0]["prompt"]) + len(recs[0]["generated"])
        bad = dict(payloads[rid], rows=ctx)
        with pytest.raises(ResumeIncompatible, match="rows"):
            dst.accept_migration(recs, source="src", kv={rid: bad})
        assert not dst._requests

    def test_int8_kv_handoff_token_identical(self, model, params):
        """int8-KV pools ship payload + scales (the payload tree mirrors
        the pool tree) and the handed-off continuation matches the
        colocated int8 engine — the quantized blocks travel bit-exactly,
        so even the weaker int8 parity bar is met exactly. A kv-bits
        mismatch (int8 payload into an f32 pool) refuses typed."""
        reqs = _reqs(n=2)
        q = {"kv_cache_bits": 8}
        base = _serving(model, params, config=q).run(
            [(p.copy(), k) for p, k in reqs])
        src = _serving(model, params, config=q, role="prefill")
        dst = _serving(model, params, config=q, role="decode")
        rids = _prefill_all(src, reqs)
        payloads = src.export_kv(rids)
        pl = payloads[rids[0]]
        assert pl["geometry"]["kv_bits"] == 8
        assert {"k", "v", "k_scale", "v_scale"} <= set(pl["data"])
        recs = src.release_requests(rids)
        dst.accept_migration(recs, source="src", kv=payloads)
        outs = _run_to_done(dst, rids)
        agree = exact = total = 0
        for (p, _), rid in zip(reqs, rids):
            # outputs are prompt + generated: score only the GENERATED
            # tail. int8 bar: first tokens exact, >0.9 greedy agreement
            # — the byte-exact handoff clears the exact bar today, the
            # weaker floor is the contract
            a = np.asarray(base[rid])[len(p):]
            b = np.asarray(outs[rid])[len(p):]
            np.testing.assert_array_equal(a[:4], b[:4])
            n = min(len(a), len(b))
            agree += int((a[:n] == b[:n]).sum())
            exact += int(np.array_equal(a, b))
            total += n
        assert agree / total > 0.9
        assert exact == len(base)       # today: bit-exact state, exact
        # cross-bits: the f32 engine's pool tree has no scale leaves
        f32 = _serving(model, params)
        rids2 = _prefill_all(src, reqs)
        payloads2 = src.export_kv(rids2)
        recs2 = src.release_requests(rids2)
        with pytest.raises(ResumeIncompatible):
            f32.accept_migration(recs2, source="src", kv=payloads2)

    def test_handoff_mid_chunked_prefill(self, model, params):
        """A chunked-prefill request handed off MID-PROMPT ships only the
        rows it has cached; the receiver's tail span finishes the prompt
        and the continuation still matches the colocated engine."""
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, 128, size=(60,)).astype(np.int32)
        base = _serving(model, params).run([(prompt.copy(), 6)])
        src = _serving(model, params, role="prefill",
                       prefill_token_budget=16)
        dst = _serving(model, params, role="decode")
        rid = src.add_request(prompt, max_new_tokens=6)
        req = src._requests[rid]
        for _ in range(5):                # land the first 16-token chunk
            src.step()
            if req.cached_rows > 0:
                break
        assert not req.prefill_done and 0 < req.cached_rows < 60
        payloads = src.export_kv([rid])
        assert payloads[rid]["rows"] == req.cached_rows
        recs = src.release_requests([rid])
        dst.accept_migration(recs, source="src", kv=payloads)
        outs = _run_to_done(dst, [rid])
        np.testing.assert_array_equal(base[rid], outs[rid])

    def test_handoff_onto_live_prefix_cache(self, model, params):
        """A receiver with a warm prefix cache takes the KV import
        verbatim (the import skips prefix matching — its rows are
        already exact) and both the handed-off request and later
        cache-hitting admissions stay token-identical."""
        rng = np.random.default_rng(11)
        prompt = rng.integers(0, 128, size=(33,)).astype(np.int32)
        base = _serving(model, params).run([(prompt.copy(), 6)])
        src = _serving(model, params, role="prefill")
        dst = _serving(model, params, enable_prefix_cache=True,
                       num_blocks=32)
        # warm the receiver's prefix cache with the same prompt (outputs
        # are prompt + generated, so the warm run is a strict prefix)
        warm = dst.run([(prompt.copy(), 4)])
        np.testing.assert_array_equal(base[0][:len(prompt) + 4], warm[0])
        rid = _prefill_all(src, [(prompt.copy(), 6)])[0]
        payloads = src.export_kv([rid])
        recs = src.release_requests([rid])
        dst.accept_migration(recs, source="src", kv=payloads)
        outs = _run_to_done(dst, [rid])
        np.testing.assert_array_equal(base[0], outs[rid])
        # and the cache still serves fresh admissions correctly
        again = dst.run([(prompt.copy(), 6)])
        np.testing.assert_array_equal(base[0], list(again.values())[0])


# ---------------------------------------------------------------------------
# role-aware routing: prefill tier -> decode tier, interop, decommission
# ---------------------------------------------------------------------------

class TestRoleRouting:
    def test_prefill_role_engine_never_decodes(self, model, params):
        """The role contract at the engine: a prefill-role engine samples
        the FIRST token (prefill output) and then parks — decode quanta
        never run, so the request never finishes there."""
        with pytest.raises(ValueError, match="role"):
            _serving(model, params, role="bogus")
        src = _serving(model, params, role="prefill")
        rid = src.add_request(np.arange(9, dtype=np.int32),
                              max_new_tokens=4)
        for _ in range(25):
            src.step()
        req = src._requests[rid]
        assert req.prefill_done and len(req.generated) == 1
        assert not src.scheduler.done     # parked, not lost

    def test_router_disagg_end_to_end_token_identical(self, tmp_path,
                                                      model, params):
        """prefill+decode fleet through the REAL router: new requests
        land on the prefill tier, the sweep hands every prefill-done
        request (KV bytes attached) to the decode tier, outputs match
        the single colocated engine exactly, and the role gauges /
        handoff counters tell the story."""
        reqs = _reqs(n=4, lens=(7, 21, 12, 30), news=(8, 6, 9, 5))
        base = _serving(model, params, max_seqs=4).run(
            [(p.copy(), k) for p, k in reqs])
        router = ServingRouter(RouterConfig(
            store_dir=str(tmp_path / "store"),
            drain_dir=str(tmp_path / "drains")))
        router.register("pre0", _serving(model, params, role="prefill"),
                        role="prefill")
        router.register("dec0", _serving(model, params, role="decode"),
                        role="decode")
        import collections
        pending = collections.deque(reqs)
        outs, rounds = {}, 0
        while pending or not router.done:
            while pending:
                p, k = pending[0]
                try:
                    router.add_request(p, k)
                except AdmissionRejected:
                    break
                pending.popleft()
            for r in router.step():
                outs[r.rid] = r.output
            rounds += 1
            assert rounds < 300, "disagg router did not converge"
        st = router.stats()
        assert st["handoffs"] == len(reqs)
        assert st["handoff_fallbacks"] == 0
        assert st["lost_requests"] == 0
        assert st["handoff_bytes"] > 0 and st["handoff_ms"] > 0
        fs = router.fleet_stats()
        assert fs["fleet_prefill_replicas"] == 1
        assert fs["fleet_decode_replicas"] == 1
        assert fs["fleet_both_replicas"] == 0
        hops = rb_events.history("request_handoff")
        assert len(hops) == len(reqs)
        assert all(e["src"] == "pre0" and e["dst"] == "dec0"
                   and e["kv"] for e in hops)
        assert set(outs) == set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged across the disagg hop")

    def test_old_no_role_heartbeat_interops_as_both(self, tmp_path):
        """A pre-ISSUE-19 replica publishes ``role: "replica"`` (or no
        meta at all): the router must treat it as "both" — admissible
        for new requests AND a valid decode target."""
        from deepspeed_tpu.analysis.serving_lint import _StubReplica
        router = ServingRouter(RouterConfig(
            store_dir=str(tmp_path / "store"),
            drain_dir=str(tmp_path / "drains")))
        c = router.config
        old = _StubReplica("old0", c.store_dir, c.drain_dir)
        assert old.meta()["role"] == "replica"      # the old string
        router.register_handle(old)
        assert router._role_of(old) == "both"
        rid = router.add_request(np.arange(4, dtype=np.int32), 4)
        assert router._placement[rid] == "old0"
        assert router.fleet_stats()["fleet_both_replicas"] == 1

    def test_new_requests_prefer_prefill_capable_replicas(self, tmp_path):
        """Admission order: decode-role replicas only see handoffs — a
        NEW request goes to the prefill tier even when the decode
        replica is less loaded; with ONLY decode replicas alive the
        router still admits (serving beats shedding)."""
        from deepspeed_tpu.analysis.serving_lint import _StubReplica
        router = ServingRouter(RouterConfig(
            store_dir=str(tmp_path / "store"),
            drain_dir=str(tmp_path / "drains")))
        c = router.config

        class _RoleStub(_StubReplica):
            def __init__(self, *a, role="both", **kw):
                super().__init__(*a, **kw)
                self.role = role

        pre = _RoleStub("pre0", c.store_dir, c.drain_dir, role="prefill")
        dec = _RoleStub("dec0", c.store_dir, c.drain_dir, role="decode")
        router.register_handle(pre)
        router.register_handle(dec)
        # load the prefill replica: it must STILL win new admissions
        for _ in range(3):
            rid = router.add_request(np.arange(4, dtype=np.int32), 4)
            assert router._placement[rid] == "pre0"
        pre.dead = True                   # confirmed death out-of-band
        rid = router.add_request(np.arange(4, dtype=np.int32), 4)
        assert router._placement[rid] == "dec0"     # fallback, not a shed

    def test_decommission_drains_and_retires_heartbeat(self, tmp_path):
        """Planned scale-down: in-flight work fails over to survivors
        (zero lost) and the heartbeat is retired so dead registry
        entries don't accumulate across scale cycles."""
        from deepspeed_tpu.analysis.serving_lint import _StubReplica

        class _KillableStub(_StubReplica):
            def kill(self):
                self.killed_t = self._clock()
                self.die()

        t = [0.0]
        router = ServingRouter(RouterConfig(
            store_dir=str(tmp_path / "store"),
            drain_dir=str(tmp_path / "drains"), clock=lambda: t[0]))
        c = router.config
        r0 = _KillableStub("r0", c.store_dir, c.drain_dir, clock=c.clock,
                           service_rate=0)
        r1 = _KillableStub("r1", c.store_dir, c.drain_dir, clock=c.clock)
        router.register_handle(r0)
        router.register_handle(r1)
        for _ in range(2):
            router.add_request(np.arange(4, dtype=np.int32), 8)
        r0.publish()
        r1.publish()
        assert "r0" in router._registry.live_hosts()
        router.decommission("r0")
        st = router.stats()
        assert st["lost_requests"] == 0.0
        assert st["migrated"] == 2.0
        assert "r0" not in router._registry.live_hosts()   # retired
        assert router.replica_inflight()["r1"] == 2


# ---------------------------------------------------------------------------
# the kv_handoff fault seam: fail + corrupt degrade to re-prefill
# ---------------------------------------------------------------------------

class TestHandoffFaultSeam:
    def test_schedule_validation(self):
        with pytest.raises(ValueError):
            FaultSchedule([{"kind": "kv_handoff"}])   # needs at/rate

    def test_fail_and_corrupt_degrade_to_reprefill(self, tmp_path,
                                                   model, params):
        """Handoff 0 is corrupted in flight (caught by the crc on the
        receiver — typed refusal, re-prefill), handoff 1 fails outright
        (the bytes never arrive, the record does). Both continuations
        still finish TOKEN-IDENTICAL to the fault-free engine: the seam
        degrades throughput, never correctness."""
        reqs = _reqs(n=2)
        base = _serving(model, params).run([(p.copy(), k) for p, k in reqs])
        inj = FaultInjector(FaultSchedule([
            {"kind": "kv_handoff", "at": 0, "mode": "corrupt"},
            {"kind": "kv_handoff", "at": 1},
        ], seed=0))
        rb_faults.install(inj)
        router = ServingRouter(RouterConfig(
            store_dir=str(tmp_path / "store"),
            drain_dir=str(tmp_path / "drains")))
        router.register("pre0", _serving(model, params, role="prefill"))
        router.register("dec0", _serving(model, params, role="decode"))
        import collections
        pending = collections.deque(reqs)
        outs, rounds = {}, 0
        while pending or not router.done:
            while pending:
                p, k = pending[0]
                try:
                    router.add_request(p, k)
                except AdmissionRejected:
                    break
                pending.popleft()
            for r in router.step():
                outs[r.rid] = r.output
            rounds += 1
            assert rounds < 300, "faulted disagg router did not converge"
        st = router.stats()
        assert st["handoffs"] == 2 and st["handoff_fallbacks"] == 2
        assert st["lost_requests"] == 0
        assert {r["kind"] for r in inj.fired} == {"kv_handoff"}
        assert len(inj.fired) == 2
        hops = rb_events.history("request_handoff")
        assert [e["kv"] for e in hops] == [False, False]
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} decoded garbage under the seam")


# ---------------------------------------------------------------------------
# the FleetController: sustained pressure scales up, lull drains, zero lost
# ---------------------------------------------------------------------------

def _fleet_fixture(tmp_path, t, **cfg_kw):
    from deepspeed_tpu.analysis.serving_lint import _StubReplica

    class _KillableStub(_StubReplica):
        def kill(self):
            self.killed_t = self._clock()
            self.die()

    router = ServingRouter(RouterConfig(
        store_dir=str(tmp_path / "store"),
        drain_dir=str(tmp_path / "drains"), clock=lambda: t[0]))
    c = router.config
    made = []

    def spawn(name, role):
        rep = _KillableStub(name, c.store_dir, c.drain_dir, clock=c.clock,
                            capacity=2, service_rate=1)
        made.append(rep)
        return rep

    cfg = FleetConfig(**dict(dict(
        role="both", min_replicas=1, max_replicas=3, scale_up_load=1.0,
        scale_up_after=2, scale_down_load=0.05, scale_down_after=2,
        cooldown_ticks=1), **cfg_kw))
    ctl = FleetController(router, spawn, cfg)
    return router, ctl, spawn, made, _KillableStub


class TestFleetController:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="role"):
            FleetConfig(role="frontend")
        with pytest.raises(ValueError, match="min_replicas"):
            FleetConfig(min_replicas=4, max_replicas=2)
        with pytest.raises(ValueError, match="band|flap"):
            FleetConfig(scale_up_load=0.5, scale_down_load=0.5)

    def test_bootstrap_below_min(self, tmp_path):
        """An empty tier is this controller's job too: it spawns up to
        min_replicas even with no load signal to average."""
        t = [0.0]
        router, ctl, _, made, _ = _fleet_fixture(
            tmp_path, t, min_replicas=2)
        name = ctl.tick()
        assert name == "auto-both-0" and len(router.replicas) == 1
        made[0].publish()
        t[0] += 1.0
        assert ctl.tick() is None            # cooldown tick
        t[0] += 1.0
        assert ctl.tick() == "auto-both-1"   # second bootstrap spawn
        assert ctl.stats()["scale_ups"] == 2.0

    def test_burst_scales_up_lull_drains_zero_lost(self, tmp_path):
        """The full loop: sustained pressure doubles the tier, the lull
        drains it back to min through decommission (integrity-chain
        drain + failover), and every admitted request completes."""
        t = [0.0]
        router, ctl, spawn, made, Stub = _fleet_fixture(tmp_path, t)
        c = router.config
        r0 = Stub("r0", c.store_dir, c.drain_dir, clock=c.clock,
                  capacity=2, service_rate=1)
        router.register_handle(r0)
        burst = [(np.arange(4, dtype=np.int32), 4) for _ in range(10)]
        import collections
        pending = collections.deque(burst)
        done = 0
        peak = 1
        for _ in range(60):
            while pending:
                try:
                    router.add_request(*pending[0])
                except AdmissionRejected:
                    break
                pending.popleft()
            done += len(router.step())
            ctl.tick()
            live = int(router.fleet_stats()["fleet_live"])
            peak = max(peak, live)
            t[0] += 1.0
            if done == len(burst) and not pending and live == 1:
                break
        assert done == len(burst)
        assert router.stats()["lost_requests"] == 0.0
        assert peak >= 2, "the burst never scaled the tier up"
        assert int(router.fleet_stats()["fleet_live"]) == 1
        st = ctl.stats()
        assert st["scale_ups"] >= 1 and st["scale_downs"] >= 1
        assert rb_events.history("fleet_scale_up")
        assert rb_events.history("fleet_scale_down")
        # scaled-down replicas' heartbeats are retired, not stale
        assert router._registry.live_hosts() == ["r0"] or \
            len(router._registry.live_hosts()) == 1

    def test_foreign_host_never_touched(self, tmp_path):
        """A heartbeat from a host this router doesn't drive (shared
        store) is tier load but never a decommission victim."""
        from deepspeed_tpu.elasticity.rendezvous import FileRendezvous
        t = [0.0]
        router, ctl, _, made, Stub = _fleet_fixture(
            tmp_path, t, scale_down_after=1, cooldown_ticks=0)
        c = router.config
        r0 = Stub("r0", c.store_dir, c.drain_dir, clock=c.clock)
        router.register_handle(r0)
        foreign = FileRendezvous(c.store_dir, "foreign0",
                                 clock=lambda: t[0])
        for _ in range(6):
            foreign.heartbeat(meta={"queue_depth": 0, "running": 0,
                                    "capacity": 4})
            r0.publish()
            router.step()
            ctl.tick()
            t[0] += 1.0
        # the controller observed the foreign host's load but never
        # tried to kill it — only router-driven replicas are victims
        assert "foreign0" in router._registry.live_hosts()

    def test_spawn_refusal_is_not_a_scale_event(self, tmp_path):
        t = [0.0]
        router, ctl, _, made, _ = _fleet_fixture(tmp_path, t)
        ctl.spawn = lambda name, role: None    # deployment out of quota
        assert ctl.tick() is None              # bootstrap refused
        assert ctl.stats()["scale_ups"] == 0.0
        assert len(router.replicas) == 0


# ---------------------------------------------------------------------------
# the handoff-recompute corpus twin (the defect this PR exists to prevent)
# ---------------------------------------------------------------------------

class TestHandoffRecomputeCorpus:
    def test_defect_fires_ttft_growth(self):
        from deepspeed_tpu.analysis.serving_lint import audit_handoff
        report = audit_handoff(kv=False)
        assert not report.ok
        assert [f.rule for f in report.findings] == ["ttft-growth"]
        sim = report.meta
        assert sim["handoffs"] > 0
        assert sim["handoff_fallbacks"] == sim["handoffs"]  # all re-paid
        ttfts = sim["decode_ttfts"]
        assert all(b >= a for a, b in zip(ttfts, ttfts[1:]))

    def test_kv_twin_passes(self):
        from deepspeed_tpu.analysis.serving_lint import audit_handoff
        report = audit_handoff(kv=True)
        assert report.ok, [f.rule for f in report.findings]
        assert report.meta["handoffs"] > 0
        assert report.meta["handoff_fallbacks"] == 0
        assert report.meta["lost"] == 0

    def test_cli_both_directions(self, capsys):
        from deepspeed_tpu.analysis.serving_lint import main as lint_main
        assert lint_main(["--handoff"]) == 1
        assert "ttft-growth" in capsys.readouterr().out
        assert lint_main(["--handoff", "--kv"]) == 0
        assert "OK" in capsys.readouterr().out

    def test_corpus_entry_registered(self):
        from deepspeed_tpu.analysis.corpus import run_corpus
        assert not run_corpus("handoff-recompute").ok


# ---------------------------------------------------------------------------
# slow: tp=2 -> tp=2 handoff, engine-backed autoscale soak
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestDisaggSlow:
    def test_tp2_to_tp2_handoff_token_identical(self, model, params):
        """Sharded pools hand off too: the export assembles the full
        head dim (logical bytes, mesh-independent), the tp=2 receiver
        re-shards on scatter, and the continuation matches the tp=2
        colocated engine exactly."""
        from deepspeed_tpu.parallel import MeshPlan, build_mesh

        def _mesh():
            return build_mesh(MeshPlan(tensor=2),
                              devices=jax.devices()[:2])

        reqs = _reqs(n=2)
        base = _serving(model, params, mesh=_mesh()).run(
            [(p.copy(), k) for p, k in reqs])
        src = _serving(model, params, mesh=_mesh(), role="prefill")
        dst = _serving(model, params, mesh=_mesh(), role="decode")
        rids = _prefill_all(src, reqs)
        payloads = src.export_kv(rids)
        # logical geometry: the payload carries the FULL head count
        assert payloads[rids[0]]["geometry"]["kv_heads"] == 2
        recs = src.release_requests(rids)
        dst.accept_migration(recs, source="src", geometry={"tp": 2},
                             kv=payloads)
        outs = _run_to_done(dst, rids)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged across the tp2 handoff")

    def test_autoscale_soak_engine_backed(self, tmp_path, model, params):
        """Burst-then-lull over REAL engines: the controller doubles the
        tier under pressure, drains it on the lull, and every request's
        output matches the single-engine baseline — scale events never
        cost tokens."""
        reqs = _reqs(n=10, lens=(7, 21, 12, 30, 16),
                     news=(8, 6, 9, 5, 7))
        base = _serving(model, params, max_seqs=4).run(
            [(p.copy(), k) for p, k in reqs])
        router = ServingRouter(RouterConfig(
            store_dir=str(tmp_path / "store"),
            drain_dir=str(tmp_path / "drains")))
        router.register("r0", _serving(model, params, max_queue=4))
        ctl = FleetController(
            router, lambda name, role: _serving(model, params,
                                                max_queue=4),
            FleetConfig(role="both", min_replicas=1, max_replicas=3,
                        scale_up_load=1.0, scale_up_after=2,
                        scale_down_load=0.05, scale_down_after=3,
                        cooldown_ticks=1))
        import collections
        pending = collections.deque(reqs)
        outs, rounds, peak = {}, 0, 1
        while pending or not router.done:
            while pending:
                p, k = pending[0]
                try:
                    router.add_request(p, k)
                except AdmissionRejected:
                    break
                pending.popleft()
            for r in router.step():
                outs[r.rid] = r.output
            ctl.tick()
            peak = max(peak, int(router.fleet_stats()["fleet_live"]))
            rounds += 1
            assert rounds < 600, "autoscale soak did not converge"
        for _ in range(12):                 # the lull drains the tier
            router.step()
            ctl.tick()
        assert router.stats()["lost_requests"] == 0.0
        assert peak >= 2, "the burst never scaled the tier"
        assert int(router.fleet_stats()["fleet_live"]) == 1
        assert set(outs) == set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged across scale events")
