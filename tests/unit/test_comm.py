"""Collectives facade tests on the 8-device CPU mesh (reference:
tests/unit/comm/test_dist.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.comm.schedule import shard_map_compat


@pytest.fixture()
def mesh1d(devices8):
    return Mesh(np.asarray(devices8), ("data",))


def _run(mesh, fn, x, in_spec, out_spec):
    # jax.shard_map only landed on the top-level namespace later; route
    # through the package's version-compat wrapper (the PR-15 ring_attention
    # mold) with EVERY mesh axis manual — classic shard_map semantics on
    # both spellings.
    f = jax.jit(shard_map_compat(fn, mesh, in_specs=in_spec,
                                 out_specs=out_spec,
                                 manual_axes=mesh.axis_names))
    return f(x)


def test_psum(mesh1d):
    x = jnp.arange(8.0)
    out = _run(mesh1d, lambda v: comm.psum(v, "data"), x, P("data"), P("data"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))


def test_pmean(mesh1d):
    x = jnp.arange(8.0)
    out = _run(mesh1d, lambda v: comm.pmean(v, "data"), x, P("data"), P("data"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, x.mean()))


def test_all_gather(mesh1d):
    x = jnp.arange(8.0)
    out = _run(mesh1d, lambda v: comm.all_gather(v, "data"), x, P("data"), P("data"))
    # each shard gathers the full vector -> output global shape (8*8,)
    assert out.shape == (64,)
    np.testing.assert_allclose(np.asarray(out)[:8], np.arange(8.0))


def test_reduce_scatter(mesh1d):
    # every shard holds [0..7]; psum_scatter sums -> 8*x, shard i keeps elem i
    x = jnp.tile(jnp.arange(8.0), (8,))
    out = _run(mesh1d, lambda v: comm.reduce_scatter(v, "data"), x, P("data"), P("data"))
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 8)


def test_all_to_all(mesh1d):
    x = jnp.arange(64.0)  # shard i holds [8i..8i+8)
    out = _run(mesh1d,
               lambda v: comm.all_to_all(v, "data", split_axis=0, concat_axis=0),
               x, P("data"), P("data"))
    got = np.asarray(out).reshape(8, 8)
    np.testing.assert_allclose(got, np.arange(64).reshape(8, 8).T)


def test_ppermute_ring(mesh1d):
    x = jnp.arange(8.0)
    perm = [(i, (i + 1) % 8) for i in range(8)]
    out = _run(mesh1d, lambda v: comm.ppermute(v, "data", perm), x, P("data"), P("data"))
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_broadcast(mesh1d):
    x = jnp.arange(8.0)
    out = _run(mesh1d, lambda v: comm.broadcast(v, "data", src_index=3), x,
               P("data"), P("data"))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_comms_logger_records():
    comm.comms_logger.configure(enabled=True)
    comm.comms_logger.reset()
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    x = jnp.arange(8.0)
    _run(mesh, lambda v: comm.psum(v, "data"), x, P("data"), P("data"))
    summary = comm.log_summary()
    assert "all_reduce" in summary
    comm.comms_logger.configure(enabled=False)


def test_barrier_runs():
    comm.barrier()
