"""graft-race tests: static lock-discipline rules on fixture snippets
(each rule both directions), explorer determinism + replay, the seeded
corpus twins, scheduler-instrumented vs uninstrumented parity, and the
two historical races (PR 13's ``__del__``-rmtree chunk-dir race, the
abandoned-watchdog stale dispatch) as permanent deterministic schedules."""

import os
import textwrap
import types

import numpy as np
import pytest

from deepspeed_tpu.analysis import race_lint
from deepspeed_tpu.analysis.race_lint import audit_schedules, scan_source
from deepspeed_tpu.robustness import sched as rs


def _rules(report):
    return {f.rule for f in report.findings}


def _snippet(src):
    return textwrap.dedent(src)


# --------------------------------------------------------------------------
# face 1: each static rule, defect and corrected twin
# --------------------------------------------------------------------------

class TestUnlockedSharedWrite:
    def test_inconsistent_discipline_flagged(self):
        rep = scan_source(_snippet("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def reset(self):
                    self._n = 0
        """))
        assert "unlocked-shared-write" in _rules(rep)
        f = next(f for f in rep.findings
                 if f.rule == "unlocked-shared-write")
        assert f.ident == "Counter._n"
        assert "reset" in f.message

    def test_consistent_discipline_clean(self):
        rep = scan_source(_snippet("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def reset(self):
                    with self._lock:
                        self._n = 0
        """))
        assert "unlocked-shared-write" not in _rules(rep)

    def test_both_sides_write_flagged_with_provenance(self):
        rep = scan_source(_snippet("""
            import threading

            class Worker:
                def __init__(self):
                    self._status = None
                    self._t = None

                def start(self):
                    self._t = threading.Thread(target=self._run, daemon=True)
                    self._t.start()

                def _run(self):
                    self._status = "done"

                def restart(self):
                    self._status = None
        """))
        found = [f for f in rep.findings
                 if f.rule == "unlocked-shared-write"]
        assert [f.ident for f in found] == ["Worker._status"]
        assert "thread entry" in found[0].message

    def test_single_writer_epoch_pattern_exempt(self):
        # the serving recovery-epoch idiom: one side rebinds, the other
        # only reads — GIL-atomic, deliberately not a finding
        rep = scan_source(_snippet("""
            import threading

            class Poller:
                def __init__(self):
                    self._epoch = 0
                    threading.Thread(target=self._run, daemon=True).start()

                def _run(self):
                    e = self._epoch
                    return e

                def bump(self):
                    self._epoch += 1
        """))
        assert "unlocked-shared-write" not in _rules(rep)


class TestLockOrderCycle:
    _bad = """
        import threading

        class Pair:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def ab(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def ba(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """

    def test_opposite_orders_flagged(self):
        rep = scan_source(_snippet(self._bad))
        assert "lock-order-cycle" in _rules(rep)
        f = next(f for f in rep.findings if f.rule == "lock-order-cycle")
        assert "Pair._a_lock" in f.message and "Pair._b_lock" in f.message

    def test_consistent_order_clean(self):
        rep = scan_source(_snippet(self._bad.replace(
            "with self._b_lock:\n                    with self._a_lock:",
            "with self._a_lock:\n                    with self._b_lock:")))
        assert "lock-order-cycle" not in _rules(rep)


class TestThreadLeak:
    def test_unjoined_nondaemon_flagged(self):
        rep = scan_source(_snippet("""
            import threading

            class Spawner:
                def go(self):
                    t = threading.Thread(target=self._run)
                    t.start()

                def _run(self):
                    pass
        """))
        assert "thread-leak" in _rules(rep)
        assert not rep.ok

    def test_joined_nondaemon_clean(self):
        rep = scan_source(_snippet("""
            import threading

            class Spawner:
                def go(self):
                    t = threading.Thread(target=self._run)
                    t.start()
                    t.join()

                def _run(self):
                    pass
        """))
        assert "thread-leak" not in _rules(rep)

    def test_daemon_touching_filesystem_warns(self):
        rep = scan_source(_snippet("""
            import os
            import threading

            class Cleaner:
                def go(self):
                    threading.Thread(target=self._run, daemon=True).start()

                def _run(self):
                    os.unlink("scratch.bin")
        """))
        found = [f for f in rep.findings if f.rule == "thread-leak"]
        assert found and found[0].severity == "warning"
        assert rep.ok          # warning severity: inventory, not a gate

    def test_daemon_without_filesystem_clean(self):
        rep = scan_source(_snippet("""
            import threading

            class Ticker:
                def go(self):
                    threading.Thread(target=self._run, daemon=True).start()

                def _run(self):
                    return 1 + 1
        """))
        assert "thread-leak" not in _rules(rep)


class TestBlockingUnderLock:
    def test_result_under_lock_flagged(self):
        rep = scan_source(_snippet("""
            import threading

            class Waiter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._fut = None

                def wait(self):
                    with self._lock:
                        return self._fut.result()
        """))
        assert "blocking-under-lock" in _rules(rep)

    def test_result_outside_lock_and_str_join_clean(self):
        rep = scan_source(_snippet("""
            import threading

            class Fine:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._fut = None

                def wait(self):
                    with self._lock:
                        fut = self._fut
                    return fut.result()

                def render(self, names):
                    with self._lock:
                        return ", ".join(names)
        """))
        assert "blocking-under-lock" not in _rules(rep)


class TestPackageScan:
    def test_package_clean_even_without_baseline(self):
        # the acceptance gate: after this PR's hygiene fixes the tree has
        # zero findings to allowlist (the checked-in baseline is empty)
        rep = race_lint.scan_package()
        assert rep.ok, rep.summary()

    def test_baseline_suppresses_known_findings(self):
        src = _snippet("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    with self._lock:
                        self._n += 1

                def reset(self):
                    self._n = 0
        """)
        rep = scan_source(src)
        assert not rep.ok
        rep2 = scan_source(src)
        rep2.apply_baseline(rep.baseline_dict())
        assert rep2.ok and len(rep2.suppressed) == 1

    def test_inventory_census(self):
        rep = race_lint.scan_package()
        inv = rep.census["concurrency"]
        # the fleet's known entry points: serving round + telemetry worker
        assert inv["threads"]["count"] >= 2
        # swap_tensor's read/write pools + infinity's rpool/wpool
        assert inv["executors"]["count"] >= 4
        assert rep.meta["entry_points"]


# --------------------------------------------------------------------------
# face 2: determinism, replay, corpus twins
# --------------------------------------------------------------------------

class TestExplorerDeterminism:
    def test_same_seed_same_schedule_same_failure(self):
        runs = [rs.explore(race_lint.allocator_share_harness(False),
                           schedules=60, seed=7, stop_on_failure=True)
                for _ in range(2)]
        assert all(r.first_failure is not None for r in runs)
        a, b = (r.first_failure for r in runs)
        assert a.schedule_id == b.schedule_id
        assert a.replay_id == b.replay_id
        assert str(a.error) == str(b.error)

    def test_replay_reproduces_failure(self):
        res = rs.explore(race_lint.allocator_share_harness(False),
                         schedules=60, seed=7, stop_on_failure=True)
        fail = res.first_failure
        again = rs.replay(race_lint.allocator_share_harness(False),
                          fail.replay_id)
        assert again is not None
        assert str(again.error) == str(fail.error)
        assert again.replay_id == fail.replay_id

    def test_different_seeds_explore_different_schedules(self):
        h = race_lint.allocator_share_harness(True)
        r0 = rs.explore(h, schedules=5, seed=0)
        r1 = rs.explore(h, schedules=5, seed=99)
        assert r0.ok and r1.ok and r0.explored == r1.explored == 5


class TestCorpusTwins:
    @pytest.mark.parametrize("name,rule", [
        ("allocator-unlocked-share", "refcount-race"),
        ("staging-buffer-alias", "buffer-alias"),
    ])
    def test_defect_fires_corrected_holds(self, name, rule):
        bad = audit_schedules(name, correct=False, schedules=200, seed=0)
        assert not bad.ok
        assert rule in _rules(bad)
        f = next(f for f in bad.findings if f.rule == rule)
        assert f.data["replay_id"].startswith("x")
        # the printed schedule id replays to the same failure
        again = race_lint.replay_audit(name, f.data["replay_id"])
        assert again is not None
        good = audit_schedules(name, correct=True, schedules=200, seed=0)
        assert good.ok, good.summary()
        assert good.meta["explored"] >= 200


# --------------------------------------------------------------------------
# parity: instrumented vs uninstrumented single-thread execution
# --------------------------------------------------------------------------

def _drive_allocator_and_cache(alloc, cache):
    """A fixed allocator + prefix-cache workout; returns the full final
    state so instrumented and plain runs can be compared bit for bit."""
    bs = cache.block_size
    b1 = alloc.alloc(3)
    toks = np.arange(3 * bs + 1, dtype=np.int32)
    cache.insert_full(toks, b1, 3 * bs)
    m = cache.match(toks)
    cache.acquire(m, owner="r2")
    alloc.free(b1, owner="r1")           # r1 exits; cache + r2 refs remain
    alloc.free(m.blocks, owner="r2")     # r2 exits; cache refs remain
    b2 = alloc.alloc(2)
    cache.evict(1)
    alloc.free(b2)
    cache.clear()
    return (tuple(alloc._free), tuple(alloc._ref),
            tuple(sorted(cache._full)), cache.held_blocks,
            tuple(sorted(cache.stats.items())))


class TestSchedulerParity:
    def test_instrumented_single_thread_bit_for_bit(self):
        from deepspeed_tpu.inference.kv_cache import BlockAllocator
        from deepspeed_tpu.inference.prefix_cache import PrefixCache

        alloc = BlockAllocator(8)
        cache = PrefixCache(alloc, 2)
        plain = _drive_allocator_and_cache(alloc, cache)

        got = {}

        def harness(s):
            a = BlockAllocator(8)
            c = PrefixCache(a, 2)
            s.instrument(a, ["alloc", "free", "share", "refcount"])
            s.instrument(c, ["match", "acquire", "insert_full", "evict",
                             "clear"])

            def run():
                got["state"] = _drive_allocator_and_cache(a, c)

            s.spawn(run, name="solo")
            return None

        for sid in ("r0", "r1", "x0"):
            got.clear()
            assert rs.run_schedule(harness, sid) is None
            assert got["state"] == plain


# --------------------------------------------------------------------------
# historical races as permanent schedules
# --------------------------------------------------------------------------

class TestLayerStoreRmtreeRace:
    """PR 13: cyclic-GC ``__del__`` on a closed LayerStore rmtree'd the
    pid-keyed chunk dir a successor store had re-created. close() is now
    idempotent; the defect twin re-enacts the old unconditional rmtree."""

    def _harness(self, tmp_path, fixed):
        from deepspeed_tpu.runtime.infinity import LayerStore

        def harness(s):
            old = LayerStore(str(tmp_path), 2, 16, backend="nvme")
            old.close()
            # successor store: same pid => same directory name
            new = LayerStore(str(tmp_path), 2, 16, backend="nvme")
            doomed = new._dir
            bits = np.arange(16, dtype=np.uint16)

            def gc_task():
                s.point("gc:collect")
                if fixed:
                    old.close()          # idempotent no-op
                else:
                    import shutil        # the pre-fix close() body
                    shutil.rmtree(doomed, ignore_errors=True)
                s.point("gc:done")

            def writer_reader():
                new.write_param(0, bits)
                s.point("store:between-write-and-read")
                got = new.read_param(0)
                if got is None or not np.array_equal(np.asarray(got), bits):
                    raise rs.InvariantViolation(
                        "successor store lost its chunk to a stale close")

            s.spawn(gc_task, name="gc")
            s.spawn(writer_reader, name="store")
            return new.close

        return harness

    def test_fixed_close_survives_all_schedules(self, tmp_path):
        res = rs.explore(self._harness(tmp_path, fixed=True),
                         schedules=30, seed=0)
        assert res.ok, res.first_failure and res.first_failure.error

    def test_defect_twin_found_and_replays(self, tmp_path):
        res = rs.explore(self._harness(tmp_path, fixed=False),
                         schedules=30, seed=0, stop_on_failure=True)
        fail = res.first_failure
        assert fail is not None
        again = rs.replay(self._harness(tmp_path, fixed=False),
                          fail.replay_id)
        assert again is not None


class TestAbandonedWatchdogRace:
    """A round thread abandoned by the dispatch watchdog must not dispatch
    stale work after recovery. The REAL ``_with_watchdog`` runs under the
    scheduler (virtual clock: the 2 s timeout is explored, not waited);
    the fixed round re-checks the recovery epoch after its stall."""

    def _harness(self, fixed):
        from deepspeed_tpu.inference import serving as sv

        def harness(s):
            ns = types.SimpleNamespace(
                config=types.SimpleNamespace(dispatch_timeout_s=2.0),
                _round_thread=None, _epoch=0)
            state = {"value": "initial"}

            def round_body():
                epoch0 = ns._epoch
                s.sleep(10.0)            # injected stall past the watchdog
                if fixed and ns._epoch != epoch0:
                    return               # abandoned round bails (serving.py)
                state["value"] = "stale-dispatch"

            def driver():
                with s.patched(sv):
                    try:
                        sv.ServingEngine._with_watchdog(ns, round_body)
                    except sv.DecodeDispatchHang:
                        ns._epoch += 1   # _recover()'s first act
                        state["value"] = "recovered"
                    else:
                        raise rs.InvariantViolation(
                            "watchdog failed to fire on a hung round")

            s.spawn(driver, name="driver")

            def check():
                if state["value"] != "recovered":
                    raise rs.InvariantViolation(
                        "stale dispatch clobbered recovered state: "
                        f"{state['value']}")
            return check

        return harness

    def test_fixed_round_bails_on_epoch_bump(self):
        res = rs.explore(self._harness(fixed=True), schedules=30, seed=0)
        assert res.ok, res.first_failure and res.first_failure.error

    def test_defect_twin_dispatches_stale_and_replays(self):
        res = rs.explore(self._harness(fixed=False), schedules=30, seed=0,
                         stop_on_failure=True)
        fail = res.first_failure
        assert fail is not None
        assert "stale" in str(fail.error)
        again = rs.replay(self._harness(fixed=False), fail.replay_id)
        assert again is not None and str(again.error) == str(fail.error)


class TestHeartbeatTornWrite:
    """Router heartbeat-write vs failover-read: the rendezvous store's
    atomic tmp+rename means a reader NEVER loses sight of a host that has
    heartbeated (old payload or new, not neither). The defect twin writes
    in place, non-atomically — the explorer finds the torn window."""

    def _harness(self, tmp_path, fixed):
        from deepspeed_tpu.elasticity.rendezvous import FileRendezvous

        store = str(tmp_path)

        def harness(s):
            rv = FileRendezvous(store, "h0", clock=s.clock, sleep=s.sleep)
            rv.heartbeat()               # h0 exists before the race starts
            reader = FileRendezvous(store, "obs", clock=s.clock,
                                    sleep=s.sleep)

            def writer():
                for _ in range(2):
                    if fixed:
                        rv.heartbeat()   # real atomic tmp + os.replace
                    else:
                        p = os.path.join(store, "hb_h0.json")
                        with open(p, "w") as f:   # pre-atomic behavior
                            f.write('{"host": "h0",')
                            f.flush()
                            s.point("torn:mid-write")
                            f.write(' "beats": 9, "ts": 0, "schema": 1}')
                    s.point("writer:beat-done")

            def failover_read():
                for _ in range(4):
                    beats = reader.read_heartbeats()
                    if "h0" not in beats:
                        raise rs.InvariantViolation(
                            "heartbeated host vanished mid-write — a "
                            "failover read would kill a live host")
                    s.point("reader:ok")

            s.spawn(writer, name="writer")
            s.spawn(failover_read, name="failover")
            return None

        return harness

    def test_atomic_heartbeat_never_torn(self, tmp_path):
        res = rs.explore(
            self._harness(tmp_path, fixed=True), schedules=30, seed=0,
            trace_files=("elasticity/rendezvous.py",))
        assert res.ok, res.first_failure and res.first_failure.error

    def test_defect_twin_torn_window_found(self, tmp_path):
        res = rs.explore(self._harness(tmp_path, fixed=False),
                         schedules=30, seed=0, stop_on_failure=True)
        fail = res.first_failure
        assert fail is not None
        assert "vanished" in str(fail.error)


# --------------------------------------------------------------------------
# slow tier: explorer soaks (run_slow.sh, RACE_BUDGET)
# --------------------------------------------------------------------------

@pytest.mark.slow
class TestExplorerSoak:
    def test_exhaustive_sweep_finds_allocator_defect(self):
        # systematic DFS over the decision tree, not seeded sampling —
        # the defect must be reachable by enumeration too
        res = rs.explore(race_lint.allocator_share_harness(False),
                         schedules=4000, mode="exhaustive")
        assert not res.ok
        assert res.first_failure.replay_id.startswith("x")

    @pytest.mark.parametrize("name", sorted(race_lint._AUDITS))
    def test_corrected_twins_hold_over_1000_schedules(self, name):
        rep = audit_schedules(name, correct=True, schedules=1000, seed=1)
        assert rep.ok, rep.summary()
        assert rep.meta["explored"] >= 1000

    def test_cli_both_faces_end_to_end(self, capsys):
        # the acceptance-criteria invocation: static face clean against
        # the checked-in baseline, both defects proven with replay ids
        assert race_lint.main([]) == 0
        out = capsys.readouterr().out
        assert out.count("defect twin FIRES") == 2
        assert out.count("corrected twin holds") == 2
        assert "--replay x" in out


# --------------------------------------------------------------------------
# regression pins for this PR's hygiene fixes
# --------------------------------------------------------------------------

class TestHygieneFixes:
    def test_comms_logger_reset_holds_lock(self):
        # regression: reset() rebinding counts/bytes/host_ms without the
        # lock raced record() — pin that every CommsLogger maps write is
        # now disciplined (the package scan has no comm.py findings)
        with open(os.path.join(os.path.dirname(race_lint.__file__),
                               "..", "comm", "comm.py")) as f:
            rep = scan_source(f.read(), "deepspeed_tpu/comm/comm.py")
        assert "unlocked-shared-write" not in _rules(rep)

    def test_engine_close_joins_telemetry_worker(self):
        import threading

        from deepspeed_tpu.runtime.engine import Engine
        ns = types.SimpleNamespace(_tel_static_thread=None)
        assert Engine.close(ns) is True
        done = threading.Event()
        t = threading.Thread(target=done.wait, daemon=True)
        t.start()
        ns._tel_static_thread = t
        assert Engine.close(ns, timeout=0.05) is False
        assert ns._tel_static_thread is t    # handle kept for a retry
        done.set()
        assert Engine.close(ns, timeout=5.0) is True
        assert ns._tel_static_thread is None

    def test_serving_close_joins_round_thread(self):
        import threading

        from deepspeed_tpu.inference.serving import ServingEngine
        ns = types.SimpleNamespace(
            config=types.SimpleNamespace(dispatch_timeout_s=0.05),
            _round_thread=None, _draining=False)
        assert ServingEngine.close(ns) is True
        assert ns._draining is True
        hang = threading.Event()
        t = threading.Thread(target=hang.wait, daemon=True)
        t.start()
        ns._round_thread = t
        assert ServingEngine.close(ns) is False
        hang.set()
        assert ServingEngine.close(ns, timeout=5.0) is True
        assert ns._round_thread is None
