"""Chunked cross-entropy parity (memory optimization: fp32 logits never
fully materialize; math must be identical to the monolithic loss)."""

import jax
import pytest
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.models import TransformerConfig
from deepspeed_tpu.models.transformer import init_params, lm_loss


def _cfg(**kw):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla")
    base.update(kw)
    return TransformerConfig(**base)


@pytest.mark.slow
def test_chunked_loss_and_grads_match_full():
    cfg_full = _cfg(loss_chunk=0)
    cfg_chunk = _cfg(loss_chunk=16)
    params = init_params(jax.random.PRNGKey(0), cfg_full)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 128, size=(4, 64)).astype(np.int32)
    labels = ids.copy()
    labels[:, -5:] = -100  # exercise the ignore mask across chunks
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}

    lf, gf = jax.value_and_grad(
        lambda p: lm_loss(p, batch, cfg_full))(params)
    lc, gc = jax.value_and_grad(
        lambda p: lm_loss(p, batch, cfg_chunk))(params)
    np.testing.assert_allclose(float(lf), float(lc), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-5, atol=1e-6), gf, gc)


@pytest.mark.slow
def test_chunk_not_dividing_seq_falls_back_gracefully():
    cfg = _cfg(loss_chunk=24)  # 24 does not divide 64 -> largest divisor used
    params = init_params(jax.random.PRNGKey(1), cfg)
    ids = jnp.asarray(np.random.default_rng(1).integers(0, 128, (2, 64)),
                      jnp.int32)
    loss = lm_loss(params, {"input_ids": ids}, cfg)
    full = lm_loss(params, {"input_ids": ids}, _cfg(loss_chunk=0))
    np.testing.assert_allclose(float(loss), float(full), rtol=1e-6)
