"""Optimizer numerical parity vs torch.optim (reference:
tests/unit/ops/adam/test_cpu_adam.py compares against torch.optim.AdamW)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops import adam, adamw, lamb, sgd, adagrad, lion, onebit_adam


def _tree(seed=0):
    r = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(r.normal(size=(16, 8)), jnp.float32),
        "b": jnp.asarray(r.normal(size=(8,)), jnp.float32),
    }


def _grads(seed=1):
    r = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(r.normal(size=(16, 8)), jnp.float32),
        "b": jnp.asarray(r.normal(size=(8,)), jnp.float32),
    }


@pytest.mark.parametrize("adam_w_mode", [False, True])
def test_adam_matches_torch(adam_w_mode):
    torch = pytest.importorskip("torch")
    params = _tree()
    grads = _grads()
    lr, wd = 1e-2, 0.1
    opt = adam(lr=lr, weight_decay=wd, adam_w_mode=adam_w_mode,
               use_master_weights=False)
    state = opt.init(params)

    tparams = {k: torch.tensor(np.asarray(v), requires_grad=True)
               for k, v in params.items()}
    topt_cls = torch.optim.AdamW if adam_w_mode else torch.optim.Adam
    topt = topt_cls(list(tparams.values()), lr=lr, weight_decay=wd)

    for step in range(5):
        params, state = opt.update(grads, state, params)
        for k, t in tparams.items():
            t.grad = torch.tensor(np.asarray(grads[k]))
        topt.step()
    for k in params:
        np.testing.assert_allclose(
            np.asarray(params[k]), tparams[k].detach().numpy(), rtol=2e-5, atol=2e-6)


def test_sgd_momentum_matches_torch():
    torch = pytest.importorskip("torch")
    params, grads = _tree(), _grads()
    opt = sgd(lr=0.1, momentum=0.9, use_master_weights=False)
    state = opt.init(params)
    tparams = {k: torch.tensor(np.asarray(v), requires_grad=True) for k, v in params.items()}
    topt = torch.optim.SGD(list(tparams.values()), lr=0.1, momentum=0.9)
    for _ in range(3):
        params, state = opt.update(grads, state, params)
        for k, t in tparams.items():
            t.grad = torch.tensor(np.asarray(grads[k]))
        topt.step()
    for k in params:
        np.testing.assert_allclose(np.asarray(params[k]),
                                   tparams[k].detach().numpy(), rtol=1e-5, atol=1e-6)


def test_adagrad_matches_torch():
    torch = pytest.importorskip("torch")
    params, grads = _tree(), _grads()
    opt = adagrad(lr=0.05, use_master_weights=False)
    state = opt.init(params)
    tparams = {k: torch.tensor(np.asarray(v), requires_grad=True) for k, v in params.items()}
    topt = torch.optim.Adagrad(list(tparams.values()), lr=0.05, eps=1e-10)
    for _ in range(3):
        params, state = opt.update(grads, state, params)
        for k, t in tparams.items():
            t.grad = torch.tensor(np.asarray(grads[k]))
        topt.step()
    for k in params:
        np.testing.assert_allclose(np.asarray(params[k]),
                                   tparams[k].detach().numpy(), rtol=1e-5, atol=1e-6)


def test_master_weights_bf16():
    """bf16 params with fp32 master should track fp32 training closely."""
    params32, grads = _tree(), _grads()
    params16 = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params32)
    opt16 = adam(lr=1e-2, use_master_weights=True)
    opt32 = adam(lr=1e-2, use_master_weights=False)
    s16, s32 = opt16.init(params32), opt32.init(params32)
    # master initialized from fp32 originals
    p16, p32 = params16, params32
    g16 = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
    for _ in range(10):
        p16, s16 = opt16.update(g16, s16, p16)
        p32, s32 = opt32.update(grads, s32, p32)
    for k in p32:
        master = s16["master"][k]
        np.testing.assert_allclose(np.asarray(master), np.asarray(p32[k]),
                                   rtol=5e-2, atol=5e-3)


def test_lamb_trust_ratio_bounds():
    params, grads = _tree(), _grads()
    opt = lamb(lr=1e-2, use_master_weights=False)
    state = opt.init(params)
    new_params, _ = opt.update(grads, state, params)
    delta = np.abs(np.asarray(new_params["w"]) - np.asarray(params["w"]))
    assert delta.max() > 0


def test_lion_sign_update():
    params, grads = _tree(), _grads()
    opt = lion(lr=1e-2, use_master_weights=False)
    state = opt.init(params)
    new_params, _ = opt.update(grads, state, params)
    delta = np.asarray(params["w"]) - np.asarray(new_params["w"])
    # first step: update = sign((1-b1)*g) * lr
    np.testing.assert_allclose(np.abs(delta), 1e-2, rtol=1e-4)


def test_onebit_adam_warmup_matches_adam():
    params, grads = _tree(), _grads()
    ob = onebit_adam(lr=1e-2, freeze_step=100, use_master_weights=False)
    ad = adam(lr=1e-2, use_master_weights=False)
    s1, s2 = ob.init(params), ad.init(params)
    p1 = p2 = params
    for _ in range(3):  # inside warmup
        p1, s1 = ob.update(grads, s1, p1)
        p2, s2 = ad.update(grads, s2, p2)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-5, atol=1e-6)


def test_onebit_adam_compressed_stage_converges():
    """After freeze_step, optimization should still reduce a quadratic loss."""
    target = jnp.ones((8, 8))
    params = {"w": jnp.zeros((8, 8))}
    opt = onebit_adam(lr=0.05, freeze_step=5, use_master_weights=False)
    state = opt.init(params)

    def loss_fn(p):
        return jnp.mean((p["w"] - target) ** 2)

    losses = []
    for _ in range(60):
        g = jax.grad(loss_fn)(params)
        params, state = opt.update(g, state, params)
        losses.append(float(loss_fn(params)))
    assert losses[-1] < 0.1 * losses[0]


def test_lr_schedule_callable():
    params, grads = _tree(), _grads()
    sched = lambda step: 0.1 / step.astype(jnp.float32)
    opt = sgd(lr=sched, use_master_weights=False)
    state = opt.init(params)
    p1, state = opt.update(grads, state, params)
    d1 = np.asarray(params["w"] - p1["w"])
    np.testing.assert_allclose(d1, 0.1 * np.asarray(grads["w"]), rtol=1e-3, atol=1e-7)
