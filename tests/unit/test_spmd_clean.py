"""The multi-chip program must compile replication-free.

Reference counterpart: DeepSpeed has no compiler warning to watch — its
failure mode is silently-added collectives. Here XLA SPMD tells us when it
falls back to replicating a tensor ("Involuntary full rematerialization"):
at real shapes that is an activation-sized all-to-all in the hot loop, so we
treat the warning as an error. Guards VERDICT r3 weakness #1 (the
take_along_axis scatter-add in the loss path, models/transformer.py) and any
future sharding regression.

The static analyzers that grew out of this module live in
deepspeed_tpu/analysis with their tests in test_analysis.py; importing via
the utils.hlo_check shim here pins the back-compat re-export.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.models.transformer import _gold_logit, cross_entropy_loss
from deepspeed_tpu.utils.hlo_check import assert_no_spmd_replication

# quick tier: `pytest -m 'not slow'` skips this module (8-device SPMD compiles)
pytestmark = pytest.mark.slow


def test_gold_logit_matches_gather():
    # the one-hot contraction must be numerically identical to the gather
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(4, 16, 64)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 64, size=(4, 16)), jnp.int32)
    want = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    got = _gold_logit(logits, labels)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_cross_entropy_ignore_index_unchanged():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, 8, 32)).astype(np.float32))
    labels = np.asarray(rng.integers(0, 32, size=(2, 8)), np.int32)
    labels[0, :4] = -100
    loss = cross_entropy_loss(logits, jnp.asarray(labels))
    # hand-computed reference
    lp = jax.nn.log_softmax(logits, axis=-1)
    want, n = 0.0, 0
    for b in range(2):
        for s in range(8):
            if labels[b, s] != -100:
                want -= float(lp[b, s, labels[b, s]])
                n += 1
    np.testing.assert_allclose(float(loss), want / n, rtol=1e-6)


@pytest.mark.parametrize("mesh_axes", [{"fsdp": 4, "tensor": 2},
                                       {"data": 8}])
def test_train_step_compiles_without_spmd_replication(mesh_axes, devices8):
    """fsdp x tensor (and pure-dp) train steps: zero SPMD fallback warnings."""
    devices = devices8
    dp = mesh_axes.get("fsdp", 1) * mesh_axes.get("data", 1)
    model = make_model(TransformerConfig(
        vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
        max_seq_len=128, dtype=jnp.float32, attention_impl="xla"),
        name="spmd-clean")
    config = {
        "train_batch_size": 2 * dp * 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 0},
        "mesh": {"axes": mesh_axes},
        "gradient_clipping": 1.0,
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config,
                                          devices=list(devices))
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, 512, size=(config["train_batch_size"], 128), dtype=np.int32)}
    metrics = assert_no_spmd_replication(engine.train_batch, batch)
    assert np.isfinite(float(metrics["loss"]))
