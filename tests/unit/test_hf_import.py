"""HF checkpoint import parity (reference: runtime/state_dict_factory.py:189,
module_inject/load_checkpoint.py). Builds tiny randomly-initialized HF models
locally (no network), converts, and matches logits."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.hf_import import (
    export_hf_state_dict, hf_config_to_transformer, load_hf_params)
from deepspeed_tpu.models.transformer import forward

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, tie_word_embeddings=False)
    torch.manual_seed(0)
    return transformers.LlamaForCausalLM(cfg).eval(), cfg


@pytest.fixture(scope="module")
def tiny_gpt2():
    cfg = transformers.GPT2Config(
        vocab_size=96, n_embd=48, n_layer=2, n_head=4, n_positions=64)
    torch.manual_seed(0)
    return transformers.GPT2LMHeadModel(cfg).eval(), cfg


def _hf_logits(model, ids):
    with torch.no_grad():
        return model(torch.from_numpy(ids)).logits.float().numpy()


def test_llama_import_logit_parity(tiny_llama):
    model, hf_cfg = tiny_llama
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla")
    assert cfg.num_kv_heads == 2 and cfg.activation == "silu_glu"
    params = load_hf_params(model, cfg)
    ids = np.random.default_rng(0).integers(0, 128, size=(2, 16)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    theirs = _hf_logits(model, ids)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_gpt2_import_logit_parity(tiny_gpt2):
    model, hf_cfg = tiny_gpt2
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla")
    assert cfg.tie_embeddings and cfg.norm_type == "layernorm"
    params = load_hf_params(model, cfg)
    ids = np.random.default_rng(1).integers(0, 96, size=(2, 12)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    theirs = _hf_logits(model, ids)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_safetensors_dir_streaming(tmp_path, tiny_llama):
    """Sharded safetensors directory loads shard-by-shard with an index."""
    import json
    from safetensors.numpy import save_file
    model, hf_cfg = tiny_llama
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla")
    sd = {k: v.float().numpy() for k, v in model.state_dict().items()}
    keys = sorted(sd)
    half = len(keys) // 2
    shards = {"model-00001-of-00002.safetensors": {k: sd[k] for k in keys[:half]},
              "model-00002-of-00002.safetensors": {k: sd[k] for k in keys[half:]}}
    weight_map = {k: fname for fname, kv in shards.items() for k in kv}
    for fname, kv in shards.items():
        save_file(kv, tmp_path / fname)
    (tmp_path / "model.safetensors.index.json").write_text(
        json.dumps({"weight_map": weight_map}))

    params = load_hf_params(str(tmp_path), cfg)
    ids = np.random.default_rng(2).integers(0, 128, size=(1, 8)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    np.testing.assert_allclose(ours, _hf_logits(model, ids), rtol=2e-4,
                               atol=2e-4)


def test_sharded_load_tp(devices8, tiny_llama):
    """shardings= places leaves straight onto a tp=2 mesh; logits unchanged."""
    from jax.sharding import NamedSharding
    from deepspeed_tpu.parallel import (MeshPlan, build_mesh, make_rules,
                                        spec_tree)
    model, hf_cfg = tiny_llama
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla")
    mesh = build_mesh(MeshPlan(data=4, tensor=2))
    rules = make_rules(zero_stage=0, tp=True)
    from deepspeed_tpu.models.transformer import logical_axes
    from jax.sharding import PartitionSpec as P
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                             spec_tree(logical_axes(cfg), rules),
                             is_leaf=lambda x: isinstance(x, P))
    params = load_hf_params(model, cfg, shardings=shardings)
    wq = params["layers"]["wq"]
    assert not wq.sharding.is_fully_replicated
    ids = np.random.default_rng(3).integers(0, 128, size=(2, 8)).astype(np.int32)
    with mesh:
        ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    np.testing.assert_allclose(ours, _hf_logits(model, ids), rtol=2e-4,
                               atol=2e-4)


def test_gpt2_untied_lm_head():
    """A GPT-2-style checkpoint with a real (untied) lm_head must load it,
    not silently substitute the embedding."""
    cfg = transformers.GPT2Config(vocab_size=96, n_embd=48, n_layer=2,
                                  n_head=4, n_positions=64,
                                  tie_word_embeddings=False)
    torch.manual_seed(1)
    model = transformers.GPT2LMHeadModel(cfg).eval()
    with torch.no_grad():  # force head != wte
        model.lm_head.weight.normal_(std=0.02)
    tcfg = hf_config_to_transformer(cfg, dtype=jnp.float32,
                                    attention_impl="xla",
                                    tie_embeddings=False)
    params = load_hf_params(model, tcfg)
    assert not np.allclose(params["lm_head"],
                           np.ascontiguousarray(params["tok_embed"].T))
    ids = np.random.default_rng(4).integers(0, 96, size=(1, 8)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), tcfg))
    np.testing.assert_allclose(ours, _hf_logits(model, ids), rtol=2e-4,
                               atol=2e-4)
    # export round-trips the untied head too
    sd = export_hf_state_dict(params, tcfg, family="gpt2")
    assert "lm_head.weight" in sd
    params2 = load_hf_params(sd, tcfg)
    np.testing.assert_array_equal(np.asarray(params["lm_head"]),
                                  np.asarray(params2["lm_head"]))


def test_export_roundtrip(tiny_llama):
    model, hf_cfg = tiny_llama
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla")
    params = load_hf_params(model, cfg)
    sd = export_hf_state_dict(params, cfg)
    params2 = load_hf_params(sd, cfg)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 params, params2)


def test_wrong_shape_raises(tiny_llama):
    model, hf_cfg = tiny_llama
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   num_layers=3)
    with pytest.raises(ValueError):
        load_hf_params(model, cfg)


@pytest.fixture(scope="module")
def tiny_mixtral():
    cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    return transformers.MixtralForCausalLM(cfg).eval(), cfg


@pytest.fixture(scope="module")
def tiny_opt():
    cfg = transformers.OPTConfig(
        vocab_size=128, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        do_layer_norm_before=True, word_embed_proj_dim=64,
        activation_function="relu", tie_word_embeddings=True)
    torch.manual_seed(0)
    return transformers.OPTForCausalLM(cfg).eval(), cfg


def test_mixtral_import_logit_parity(tiny_mixtral):
    """BASELINE config #4 family: MoE import with per-expert stacking and
    router weights; top-2 renormalized gating matches HF exactly when no
    tokens drop (drop_tokens=False)."""
    model, hf_cfg = tiny_mixtral
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla", drop_tokens=False)
    assert cfg.num_experts == 4 and cfg.top_k == 2
    params = load_hf_params(model, cfg)
    assert params["layers"]["moe_w_in"].shape == (2, 4, 64, 96)
    ids = np.random.default_rng(2).integers(0, 128, size=(2, 16)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    theirs = _hf_logits(model, ids)
    np.testing.assert_allclose(ours, theirs, rtol=5e-4, atol=5e-4)


def test_opt_import_logit_parity(tiny_opt):
    """BASELINE config #5 family: OPT — learned positions with the +2 offset,
    relu MLP, per-projection biases, decoder-level final layernorm."""
    model, hf_cfg = tiny_opt
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla")
    assert cfg.activation == "relu" and cfg.position_type == "learned"
    params = load_hf_params(model, cfg)
    ids = np.random.default_rng(3).integers(0, 128, size=(2, 16)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    theirs = _hf_logits(model, ids)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_opt_unsupported_variants_raise():
    cfg350 = transformers.OPTConfig(
        vocab_size=64, hidden_size=32, ffn_dim=64, num_hidden_layers=1,
        num_attention_heads=2, word_embed_proj_dim=16)
    with pytest.raises(ValueError, match="word_embed_proj_dim"):
        hf_config_to_transformer(cfg350)


@pytest.fixture(scope="module")
def tiny_bloom():
    cfg = transformers.BloomConfig(
        vocab_size=128, hidden_size=64, n_layer=2, n_head=4,
        layer_norm_epsilon=1e-5, tie_word_embeddings=True)
    torch.manual_seed(0)
    return transformers.BloomForCausalLM(cfg).eval(), cfg


def test_bloom_import_logit_parity(tiny_bloom):
    """BLOOM: alibi attention, embedding layernorm, interleaved fused qkv."""
    model, hf_cfg = tiny_bloom
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla", max_seq_len=64)
    assert cfg.position_type == "alibi" and cfg.embed_norm
    params = load_hf_params(model, cfg)
    ids = np.random.default_rng(4).integers(0, 128, size=(2, 16)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    theirs = _hf_logits(model, ids)
    np.testing.assert_allclose(ours, theirs, rtol=5e-4, atol=5e-4)


def test_bloom_decode_matches_forward(tiny_bloom):
    """Alibi must also be exact in the KV-cache decode path."""
    from deepspeed_tpu.models.transformer import (decode_step, init_cache,
                                                  prefill)
    model, hf_cfg = tiny_bloom
    cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                   attention_impl="xla", max_seq_len=64)
    params = load_hf_params(model, cfg)
    ids = np.random.default_rng(5).integers(0, 128, size=(1, 8)).astype(np.int32)
    cache = init_cache(cfg, 1, 32, dtype=jnp.float32)
    last, cache = prefill(params, jnp.asarray(ids), cfg, cache)
    tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
    dec_logits, cache = decode_step(params, tok, cfg, cache)
    full = forward(params, jnp.concatenate(
        [jnp.asarray(ids), tok[:, None]], axis=1), cfg)
    np.testing.assert_allclose(np.asarray(dec_logits),
                               np.asarray(full[:, -1]), rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# encoder + GPT-J + GPT-NeoX families (VERDICT r3 item 5; reference:
# module_inject/containers/{bert,gptj,gptneox}.py)
# ---------------------------------------------------------------------------

def test_bert_import_hidden_parity():
    cfg_hf = transformers.BertConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2)
    torch.manual_seed(1)
    hf = transformers.BertModel(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    assert not cfg.causal and cfg.norm_style == "post" and not cfg.final_norm
    params = load_hf_params(hf, cfg)
    ids = np.random.default_rng(0).integers(0, 96, size=(2, 10)).astype(np.int32)
    tt = np.zeros((2, 10), np.int32)
    tt[:, 5:] = 1
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg,
                              token_type_ids=jnp.asarray(tt),
                              return_hidden=True)[0])
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long(),
                 token_type_ids=torch.from_numpy(tt).long()
                 ).last_hidden_state.float().numpy()
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_bert_padding_mask_parity():
    cfg_hf = transformers.BertConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2)
    torch.manual_seed(2)
    hf = transformers.BertModel(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    params = load_hf_params(hf, cfg)
    ids = np.random.default_rng(1).integers(0, 96, size=(2, 12)).astype(np.int32)
    mask = np.ones((2, 12), np.int32)
    mask[0, 8:] = 0
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg,
                              attention_mask=jnp.asarray(mask),
                              return_hidden=True)[0])
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long(),
                 attention_mask=torch.from_numpy(mask).long()
                 ).last_hidden_state.float().numpy()
    # padded positions' outputs are junk in both; compare valid rows
    np.testing.assert_allclose(ours[1], ref[1], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(ours[0, :8], ref[0, :8], rtol=2e-4, atol=2e-4)


def test_gptj_import_logit_parity():
    cfg_hf = transformers.GPTJConfig(
        vocab_size=96, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        rotary_dim=8, n_inner=None)
    torch.manual_seed(3)
    hf = transformers.GPTJForCausalLM(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    assert cfg.parallel_block and cfg.rotary_interleaved
    assert cfg.rotary_dim == 8 and cfg.head_bias
    params = load_hf_params(hf, cfg)
    assert "lm_head_bias" in params
    ids = np.random.default_rng(2).integers(0, 96, size=(2, 12)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    np.testing.assert_allclose(ours, _hf_logits(hf, ids), rtol=2e-4,
                               atol=2e-4)


def test_gptneox_import_logit_parity():
    cfg_hf = transformers.GPTNeoXConfig(
        vocab_size=96, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True)
    torch.manual_seed(4)
    hf = transformers.GPTNeoXForCausalLM(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    assert cfg.parallel_block and not cfg.rotary_interleaved
    assert cfg.rotary_dim == 4  # 16 * 0.25
    params = load_hf_params(hf, cfg)
    ids = np.random.default_rng(3).integers(0, 96, size=(2, 12)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    np.testing.assert_allclose(ours, _hf_logits(hf, ids), rtol=2e-4,
                               atol=2e-4)


@pytest.mark.slow
def test_gptj_decode_matches_forward():
    """The parallel-block cache path: greedy decode == argmax of full
    forward (the KV-cache/decode contract for the new families)."""
    cfg_hf = transformers.GPTJConfig(
        vocab_size=96, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        rotary_dim=8)
    torch.manual_seed(5)
    hf = transformers.GPTJForCausalLM(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    params = load_hf_params(hf, cfg)
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import make_model
    eng = deepspeed_tpu.init_inference(make_model(cfg), params=params,
                                       dtype=jnp.float32)
    ids = np.random.default_rng(4).integers(0, 96, size=(1, 8)).astype(np.int32)
    out = np.asarray(eng.generate(ids, max_new_tokens=6))
    # greedy reference via repeated full forwards
    cur = ids
    for _ in range(6):
        logits = np.asarray(forward(params, jnp.asarray(cur), cfg))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur)


def test_megatron_tp_rank_merge():
    """load_megatron_params: 2 TP-rank Megatron state dicts round-trip to
    the original tree (reference: MegatronSDLoader merge,
    state_dict_factory.py:189). qkv is per-head interleaved column-parallel;
    attention.dense / mlp output are row-parallel."""
    from deepspeed_tpu.models.transformer import TransformerConfig, init_params
    from deepspeed_tpu.models.hf_import import load_megatron_params
    cfg = TransformerConfig(vocab_size=96, hidden_size=48, num_layers=2,
                            num_heads=4, max_seq_len=32,
                            position_type="learned", norm_type="layernorm",
                            activation="gelu", tie_embeddings=True)
    params = jax.tree.map(np.asarray, init_params(jax.random.PRNGKey(0), cfg))
    nh, hd, tp = 4, 12, 2
    per = nh // tp
    ranks = [dict(), dict()]
    lay = params["layers"]
    V = cfg.vocab_size

    def col_split(w_ours, r):  # ours [in, out] -> megatron [out/tp, in]
        return np.ascontiguousarray(
            w_ours.T[r * w_ours.shape[1] // tp:(r + 1) * w_ours.shape[1] // tp])

    for r in range(tp):
        sd = ranks[r]
        sd["embedding.word_embeddings.weight"] = \
            params["tok_embed"][r * V // tp:(r + 1) * V // tp]
        sd["embedding.position_embeddings.weight"] = params["pos_embed"]
        sd["encoder.final_layernorm.weight"] = params["final_norm_scale"]
        sd["encoder.final_layernorm.bias"] = params["final_norm_bias"]
        for i in range(cfg.num_layers):
            p = f"encoder.layers.{i}."
            sd[p + "input_layernorm.weight"] = lay["ln1_scale"][i]
            sd[p + "input_layernorm.bias"] = lay["ln1_bias"][i]
            sd[p + "post_attention_layernorm.weight"] = lay["ln2_scale"][i]
            sd[p + "post_attention_layernorm.bias"] = lay["ln2_bias"][i]
            # interleaved fused qkv per rank: [per, 3, hd, H]
            q = lay["wq"][i].T.reshape(nh, hd, -1)[r * per:(r + 1) * per]
            k = lay["wk"][i].T.reshape(nh, hd, -1)[r * per:(r + 1) * per]
            v = lay["wv"][i].T.reshape(nh, hd, -1)[r * per:(r + 1) * per]
            sd[p + "attention.query_key_value.weight"] = np.ascontiguousarray(
                np.stack([q, k, v], axis=1).reshape(per * 3 * hd, -1))
            bq = lay["bq"][i].reshape(nh, hd)[r * per:(r + 1) * per]
            bk = lay["bk"][i].reshape(nh, hd)[r * per:(r + 1) * per]
            bv = lay["bv"][i].reshape(nh, hd)[r * per:(r + 1) * per]
            sd[p + "attention.query_key_value.bias"] = np.ascontiguousarray(
                np.stack([bq, bk, bv], axis=1).reshape(-1))
            # row-parallel: ours wo [in, out] -> megatron [out, in/tp]
            wo = lay["wo"][i]
            sd[p + "attention.dense.weight"] = np.ascontiguousarray(
                wo.T[:, r * wo.shape[0] // tp:(r + 1) * wo.shape[0] // tp])
            sd[p + "attention.dense.bias"] = lay["bo"][i]
            sd[p + "mlp.dense_h_to_4h.weight"] = col_split(lay["w_in"][i], r)
            F = lay["b_in"][i].shape[0]
            sd[p + "mlp.dense_h_to_4h.bias"] = \
                lay["b_in"][i][r * F // tp:(r + 1) * F // tp]
            wout = lay["w_out"][i]
            sd[p + "mlp.dense_4h_to_h.weight"] = np.ascontiguousarray(
                wout.T[:, r * F // tp:(r + 1) * F // tp])
            sd[p + "mlp.dense_4h_to_h.bias"] = lay["b_out"][i]
    merged = load_megatron_params(ranks, cfg)
    flat_a = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_b = jax.tree_util.tree_flatten_with_path(merged)[0]
    assert len(flat_a) == len(flat_b)
    for (pa, a), (pb, b) in zip(sorted(flat_a, key=lambda t: str(t[0])),
                                sorted(flat_b, key=lambda t: str(t[0]))):
        assert str(pa) == str(pb), (pa, pb)
        np.testing.assert_allclose(np.asarray(a, np.float32), b, atol=1e-6,
                                   err_msg=str(pa))


def test_roberta_import_hidden_parity():
    """RoBERTa: BERT layout with the padding_idx+1=2 position-row offset."""
    cfg_hf = transformers.RobertaConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=66, type_vocab_size=1, pad_token_id=1)
    torch.manual_seed(6)
    hf = transformers.RobertaModel(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    assert cfg.max_seq_len == 64
    params = load_hf_params(hf, cfg, family="roberta")
    # avoid the pad token (HF position ids skip pads)
    ids = np.random.default_rng(5).integers(2, 96, size=(2, 10)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg,
                              return_hidden=True)[0])
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long()).last_hidden_state.float().numpy()
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_bert_inference_engine_encode():
    """init_inference serves encoder models: engine.encode() hidden states
    match HF (the fill-mask/classification entry point)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import make_model
    cfg_hf = transformers.BertConfig(
        vocab_size=96, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2)
    torch.manual_seed(9)
    hf = transformers.BertModel(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    params = load_hf_params(hf, cfg)
    eng = deepspeed_tpu.init_inference(make_model(cfg), params=params,
                                       dtype=jnp.float32)
    ids = np.random.default_rng(8).integers(0, 96, size=(2, 10)).astype(np.int32)
    tt = np.zeros((2, 10), np.int32)
    tt[:, 6:] = 1
    ours = np.asarray(eng.encode(ids, token_type_ids=tt))
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long(),
                 token_type_ids=torch.from_numpy(tt).long()
                 ).last_hidden_state.float().numpy()
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)
    # decoder models refuse (hidden states there come from generate/forward)
    from deepspeed_tpu.models.unet import UNetConfig, make_unet_model
    eng2 = deepspeed_tpu.init_inference(
        make_unet_model(UNetConfig(base_channels=16, norm_groups=4)),
        dtype=jnp.float32)
    with pytest.raises(ValueError, match="transformer"):
        eng2.encode(ids)


def test_distilbert_import_hidden_parity():
    """DistilBERT encoder (reference: module_inject/containers/
    distil_bert.py): post-LN, no token-type embeddings."""
    cfg_hf = transformers.DistilBertConfig(
        vocab_size=96, dim=48, n_layers=2, n_heads=4, hidden_dim=64,
        max_position_embeddings=64, dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(6)
    hf = transformers.DistilBertModel(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    assert not cfg.causal and cfg.norm_style == "post"
    assert cfg.type_vocab_size == 0 and not cfg.final_norm
    params = load_hf_params(hf, cfg)
    assert "tok_type_embed" not in params
    ids = np.random.default_rng(5).integers(0, 96, size=(2, 10)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg,
                              return_hidden=True)[0])
    with torch.no_grad():
        ref = hf(torch.from_numpy(ids).long()).last_hidden_state.float().numpy()
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_gptneo_import_logit_parity_local_attention():
    """GPT-Neo (reference: module_inject/containers/gptneo.py) with a
    window_size SMALLER than the sequence — validates the per-layer band
    mask (cfg.attn_windows) against HF's real local attention, not just the
    weight mapping."""
    cfg_hf = transformers.GPTNeoConfig(
        vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
        attention_types=[[["global", "local"], 1]], window_size=4,
        max_position_embeddings=64)
    torch.manual_seed(7)
    hf = transformers.GPTNeoForCausalLM(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    assert cfg.attn_windows == (0, 4) and not cfg.qkv_bias
    params = load_hf_params(hf, cfg)
    assert "bo" in params["layers"] and "bq" not in params["layers"]
    ids = np.random.default_rng(6).integers(0, 96, size=(2, 12)).astype(np.int32)
    ours = np.asarray(forward(params, jnp.asarray(ids), cfg))
    np.testing.assert_allclose(ours, _hf_logits(hf, ids), rtol=2e-4,
                               atol=2e-4)


@pytest.mark.slow
def test_gptneo_decode_matches_forward():
    """Greedy decode crosses the local window boundary: the decode cache's
    band mask must match the full forward's."""
    cfg_hf = transformers.GPTNeoConfig(
        vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
        attention_types=[[["global", "local"], 1]], window_size=4,
        max_position_embeddings=64)
    torch.manual_seed(8)
    hf = transformers.GPTNeoForCausalLM(cfg_hf).eval()
    cfg = hf_config_to_transformer(cfg_hf, dtype=jnp.float32,
                                   attention_impl="xla")
    params = load_hf_params(hf, cfg)
    import deepspeed_tpu
    from deepspeed_tpu.models.transformer import make_model
    eng = deepspeed_tpu.init_inference(make_model(cfg), params=params,
                                       dtype=jnp.float32)
    ids = np.random.default_rng(7).integers(0, 96, size=(1, 8)).astype(np.int32)
    out = np.asarray(eng.generate(ids, max_new_tokens=6))
    cur = ids
    for _ in range(6):
        logits = np.asarray(forward(params, jnp.asarray(cur), cfg))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur)
