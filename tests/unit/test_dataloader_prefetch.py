"""Async step pipeline: PrefetchLoader + device-resident overflow accounting.

Pins the PR-2 tentpole contracts:
  * prefetch preserves batch order, including across epoch boundaries, and
    set_epoch still reshuffles through the wrapper;
  * the prefetch device_put is idempotent through engine._device_batch
    (already-placed leaves pass through untouched);
  * a 20-step fp16 run with a forced overflow at step 7 produces identical
    global_steps / skipped_steps / final params (bit-for-bit) under the
    per-step-fetch sync path, the async train_batches path, and the fused
    K-step path — overflow/skip accounting lives in the jitted state, so
    removing the host sync must not change a single bit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.dataloader import (DataLoader, PrefetchLoader,
                                              RepeatingLoader)


# --------------------------------------------------------------------------
# prefetch ordering
# --------------------------------------------------------------------------

def rows(n=12, d=4):
    return [{"x": np.full((d,), i, np.float32)} for i in range(n)]


class TestPrefetchOrder:
    def test_preserves_order_within_epoch(self):
        loader = DataLoader(rows(), batch_size=4)
        pf = PrefetchLoader(loader, put_fn=lambda b: b)
        got = [b["x"][:, 0].tolist() for b in pf]
        want = [b["x"][:, 0].tolist() for b in loader]
        assert got == want

    def test_preserves_order_across_epoch_boundary(self):
        """RepeatingLoader under prefetch: the epoch rollover happens inside
        the wrapped iterator; prefetch must not reorder around it."""
        loader = DataLoader(rows(8), batch_size=4, shuffle=True, seed=3)
        pf = PrefetchLoader(RepeatingLoader(loader), put_fn=lambda b: b,
                            depth=3)
        it = iter(pf)
        got = [next(it)["x"][:, 0].tolist() for _ in range(6)]  # 3 epochs
        ref_loader = DataLoader(rows(8), batch_size=4, shuffle=True, seed=3)
        want = []
        for epoch in range(3):
            ref_loader.set_epoch(epoch)
            want += [b["x"][:, 0].tolist() for b in ref_loader]
        assert got == want

    def test_set_epoch_reshuffles_through_wrapper(self):
        loader = DataLoader(rows(16), batch_size=4, shuffle=True, seed=0)
        pf = PrefetchLoader(loader, put_fn=lambda b: b)
        pf.set_epoch(0)
        e0 = [b["x"][:, 0].tolist() for b in pf]
        pf.set_epoch(1)
        e1 = [b["x"][:, 0].tolist() for b in pf]
        assert e0 != e1                       # reshuffled
        assert sorted(sum(e0, [])) == sorted(sum(e1, []))  # same data
        assert pf.epoch == 1

    def test_short_iterator_and_len(self):
        loader = DataLoader(rows(4), batch_size=4)
        pf = PrefetchLoader(loader, put_fn=lambda b: b, depth=8)
        assert len(pf) == 1
        assert len(list(pf)) == 1


# --------------------------------------------------------------------------
# sync vs async vs fused parity (the tentpole acceptance gate)
# --------------------------------------------------------------------------

class ToyLinear:
    """Minimal ModelSpec whose loss can be pushed to an fp16 grad overflow
    on demand through the input magnitude."""

    name = "toy-linear"

    def __init__(self, d=8):
        self.d = d

    def init(self, rng):
        return {"w": jax.random.normal(rng, (self.d, self.d),
                                       jnp.float32) * 0.1}

    @property
    def logical_axes(self):
        return {"w": None}

    def loss_fn(self, params, batch, rng, deterministic):
        y = batch["x"] @ params["w"].astype(batch["x"].dtype)
        return jnp.mean(jnp.square(y).astype(jnp.float32))


def fp16_cfg(**overrides):
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           # scale 2^8: unit-scale grads stay well inside fp16 range, the
           # boosted batch overflows deterministically
           "fp16": {"enabled": True, "initial_scale_power": 8},
           "bf16": {"enabled": False},
           "steps_per_print": 100}
    cfg.update(overrides)
    return cfg


def overflow_batches(n=20, boost_at=7):
    rng = np.random.default_rng(0)
    batches = [{"x": rng.normal(size=(16, 8)).astype(np.float32)}
               for _ in range(n)]
    # 1e8 * scale(2^8) saturates the fp16 grads -> overflow -> skipped step
    batches[boost_at] = {"x": (batches[boost_at]["x"] * 1e8
                               ).astype(np.float32)}
    return batches


def params_bits(engine):
    w = np.asarray(jax.device_get(engine.state["params"]["w"]))
    return w.view(np.uint16)


class TestSyncAsyncParity:
    def test_overflow_accounting_matches_bit_for_bit(self):
        batches = overflow_batches()

        # sync path: host fetch after every step (the pre-PR behavior)
        sync, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                            config=fp16_cfg())
        overflows = 0
        for b in batches:
            m = sync.train_batch(b)
            overflows += int(bool(np.asarray(jax.device_get(m["overflow"]))))
        assert overflows == 1

        # async path: train_batches (prefetch + bounded in-flight window),
        # no per-step host fetch anywhere
        async_, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                              config=fp16_cfg())
        async_.train_batches(iter(batches), 20)

        assert sync.global_steps == async_.global_steps == 20
        assert sync.skipped_steps == async_.skipped_steps == 1
        assert sync.get_loss_scale() == async_.get_loss_scale()
        np.testing.assert_array_equal(params_bits(sync), params_bits(async_))
        # the applied-update counter also skipped exactly the overflow step
        assert int(np.asarray(jax.device_get(async_.state["step"]))) == 19

    def test_fused_k_steps_match_bit_for_bit(self):
        """pipeline.fuse_steps=4: 5 dispatches cover 20 steps; the in-graph
        loss-scale/skip accounting threads through the unrolled program."""
        batches = overflow_batches()
        ref, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                           config=fp16_cfg())
        for b in batches:
            ref.train_batch(b)
        fused, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(),
            config=fp16_cfg(pipeline={"fuse_steps": 4, "in_flight": 2}))
        fused.train_batches(iter(batches), 20)
        assert fused.global_steps == 20
        assert fused.skipped_steps == ref.skipped_steps == 1
        np.testing.assert_array_equal(params_bits(ref), params_bits(fused))

    def test_checkpoint_roundtrips_device_skip_counter(self, tmp_path):
        batches = overflow_batches(n=10)
        e, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                         config=fp16_cfg())
        e.train_batches(iter(batches), 10)
        assert e.skipped_steps == 1
        e.save_checkpoint(str(tmp_path), tag="ck")
        e2, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                          config=fp16_cfg())
        e2.load_checkpoint(str(tmp_path), tag="ck")
        assert e2.global_steps == 10
        assert e2.skipped_steps == 1
        # keeps counting in-graph after restore
        more = overflow_batches(n=5, boost_at=2)
        e2.train_batches(iter(more), 5)
        assert e2.skipped_steps == 2


    def test_loads_legacy_checkpoint_without_skip_counter(self, tmp_path):
        """fp16 checkpoints written before the device-resident counter have
        no "skipped" leaf; load falls back and reconciles from
        client_state."""
        e, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                         config=fp16_cfg())
        e.train_batches(iter(overflow_batches(n=5, boost_at=2)), 5)
        # simulate the pre-PR on-disk layout: no skipped leaf in the state
        # tree, the skip recorded host-side only
        e.state.pop("skipped")
        e.state_shardings.pop("skipped")
        e._skipped_offset = 1
        e.save_checkpoint(str(tmp_path), tag="legacy")
        e2, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                          config=fp16_cfg())
        e2.load_checkpoint(str(tmp_path), tag="legacy")
        assert e2.global_steps == 5
        assert e2.skipped_steps == 1
        assert "skipped" in e2.state  # rebuilt; keeps counting in-graph
        e2.train_batches(iter(overflow_batches(n=5, boost_at=3)), 5)
        assert e2.skipped_steps == 2


class TestDeviceBatchIdempotent:
    def test_second_put_passes_through(self):
        e, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                         config=fp16_cfg())
        b = {"x": np.ones((16, 8), np.float32)}
        placed = e._device_batch(b)
        again = e._device_batch(placed)
        assert again["x"] is placed["x"]  # no re-dispatch of a placed leaf
