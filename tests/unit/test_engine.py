"""End-to-end engine tests on the 8-device CPU mesh.

Reference coverage model: tests/unit/runtime/zero/test_zero.py (stage parity,
world sizes), test_fp16.py (loss scaling), tests/unit/checkpoint (save/resume
parity incl. different world layout — here: different mesh/zero stage).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import Config
from deepspeed_tpu.models import TransformerConfig, make_model
from tests.conftest import make_batch


def tiny_cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla")
    base.update(kw)
    return TransformerConfig(**base)


def ds_config(**overrides):
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "steps_per_print": 100,
    }
    cfg.update(overrides)
    return cfg


def fixed_batch(n=16, s=32, vocab=64, seed=0):
    return make_batch(n, s, vocab=vocab, seed=seed)


def train_losses(config, steps=12, model=None, seed=0):
    model = model or make_model(tiny_cfg())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    batch = fixed_batch(n=config.get("train_batch_size", 16), seed=seed)
    losses = []
    for _ in range(steps):
        metrics = engine.train_batch(batch)
        losses.append(float(metrics["loss"]))
    return losses, engine


class TestTraining:
    def test_loss_decreases(self):
        losses, _ = train_losses(ds_config(), steps=15)
        assert losses[-1] < losses[0] * 0.8, losses

    @pytest.mark.slow
    def test_bf16_trains(self):
        model = make_model(tiny_cfg(dtype=jnp.bfloat16))
        losses, engine = train_losses(
            ds_config(bf16={"enabled": True}), steps=15, model=model)
        assert losses[-1] < losses[0] * 0.9
        # params stored in bf16, master in fp32
        assert engine.state["params"]["tok_embed"].dtype == jnp.bfloat16
        assert engine.state["opt"]["master"]["tok_embed"].dtype == jnp.float32

    @pytest.mark.slow
    def test_grad_accumulation_equivalence(self):
        """gas=4 over the same data must match gas=1 (reference: grad-accum
        boundary semantics)."""
        l1, e1 = train_losses(
            ds_config(train_batch_size=32, gradient_accumulation_steps=1), steps=6)
        l4, e4 = train_losses(
            ds_config(train_batch_size=32, gradient_accumulation_steps=4), steps=6)
        p1 = jax.tree.leaves(e1.state["params"])
        p4 = jax.tree.leaves(e4.state["params"])
        np.testing.assert_allclose(l1, l4, rtol=1e-4)
        for a, b in zip(p1, p4):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-4)

    @pytest.mark.slow
    def test_gradient_clipping_runs(self):
        losses, _ = train_losses(ds_config(gradient_clipping=0.5), steps=5)
        assert all(np.isfinite(l) for l in losses)

    @pytest.mark.slow
    def test_scheduler_warmup(self):
        cfg = ds_config(scheduler={"type": "WarmupLR", "params": {
            "warmup_min_lr": 0.0, "warmup_max_lr": 1e-2, "warmup_num_steps": 10}})
        losses, engine = train_losses(cfg, steps=5)
        lr = engine.get_lr()
        assert 0 < lr < 1e-2  # still warming

    @pytest.mark.slow
    def test_eval_batch(self):
        _, engine = train_losses(ds_config(), steps=2)
        loss = engine.eval_batch(fixed_batch())
        assert np.isfinite(float(loss))


@pytest.mark.slow
class TestZeroStages:
    @pytest.mark.parametrize("stage", [0, 1, 2, 3])
    def test_stage_parity(self, stage):
        """All ZeRO stages are rearrangements of the same math — identical
        losses (reference: test_zero.py parity across stages)."""
        baseline, _ = train_losses(ds_config(), steps=6)
        staged, engine = train_losses(
            ds_config(zero_optimization={
                "stage": stage, "stage3_param_persistence_threshold": 0}), steps=6)
        np.testing.assert_allclose(baseline, staged, rtol=2e-4, atol=1e-5)
        if stage >= 1:
            # optimizer state must actually be sharded over dp
            master = engine.state["opt"]["exp_avg"]["layers"]["wq"]
            axis = "fsdp" if stage >= 3 else "data"
            specs = [s for s in master.sharding.spec if s is not None]
            flat = [a for s in specs for a in (s if isinstance(s, tuple) else (s,))]
            assert axis in flat, f"stage {stage}: {master.sharding}"

    def test_stage3_params_sharded(self):
        _, engine = train_losses(
            ds_config(zero_optimization={
                "stage": 3, "stage3_param_persistence_threshold": 4096}), steps=2)
        w = engine.state["params"]["layers"]["w_in"]
        flat = [a for s in w.sharding.spec if s is not None
                for a in (s if isinstance(s, tuple) else (s,))]
        assert "fsdp" in flat
        # small params stay replicated (persistence threshold)
        norm = engine.state["params"]["final_norm_scale"]
        assert norm.sharding.is_fully_replicated

    def test_stage3_persistence_threshold_zero(self):
        cfg = ds_config(zero_optimization={
            "stage": 3, "stage3_param_persistence_threshold": 0})
        losses, _ = train_losses(cfg, steps=3)
        assert all(np.isfinite(l) for l in losses)


@pytest.mark.slow
class TestFP16:
    def test_fp16_dynamic_scaling_trains(self):
        model = make_model(tiny_cfg(dtype=jnp.float16))
        cfg = ds_config(fp16={"enabled": True, "initial_scale_power": 8},
                        bf16={"enabled": False})
        losses, engine = train_losses(cfg, steps=15, model=model)
        assert losses[-1] < losses[0]
        assert engine.get_loss_scale() >= 1.0

    def test_overflow_skips_step(self):
        """Inject an inf grad via a huge loss scale; params must not change."""
        model = make_model(tiny_cfg(dtype=jnp.float16))
        cfg = ds_config(fp16={"enabled": True, "initial_scale_power": 24,
                              "loss_scale_window": 1000},
                        bf16={"enabled": False})
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
        before_scale = engine.get_loss_scale()
        batch = fixed_batch()
        for _ in range(3):
            engine.train_batch(batch)
        # fp16 max ~65504; scale 2^24 on a ~4.x loss overflows the scaled grads
        after_scale = engine.get_loss_scale()
        assert after_scale <= before_scale  # shrank (or stayed if no overflow)


@pytest.mark.slow
class TestThreeCallAPI:
    def test_forward_backward_step(self):
        """The reference's engine.forward/backward/step loop."""
        model = make_model(tiny_cfg())
        cfg = ds_config(train_batch_size=16, gradient_accumulation_steps=2,
                        train_micro_batch_size_per_gpu=1)
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
        batch = fixed_batch(n=8)
        losses = []
        for it in range(4):
            loss = engine.forward(batch)
            engine.backward(loss)
            result = engine.step()
            if engine.is_gradient_accumulation_boundary() or result is not None:
                pass
            losses.append(float(loss))
        assert engine.global_steps == 2  # 4 micro / gas=2
        assert all(np.isfinite(l) for l in losses)


@pytest.mark.slow
class TestCheckpoint:
    def test_save_load_parity(self, tmp_path):
        cfg = ds_config()
        losses, engine = train_losses(cfg, steps=4)
        engine.save_checkpoint(str(tmp_path), tag="ck")
        # continue 3 more steps -> record
        batch = fixed_batch()
        cont = [float(engine.train_batch(batch)["loss"]) for _ in range(3)]

        model = make_model(tiny_cfg())
        engine2, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
        engine2.load_checkpoint(str(tmp_path), tag="ck")
        assert engine2.global_steps == 4
        resumed = [float(engine2.train_batch(batch)["loss"]) for _ in range(3)]
        np.testing.assert_allclose(cont, resumed, rtol=2e-4, atol=1e-5)

    def test_latest_tag(self, tmp_path):
        _, engine = train_losses(ds_config(), steps=2)
        engine.save_checkpoint(str(tmp_path))
        assert os.path.exists(tmp_path / "latest")
        model = make_model(tiny_cfg())
        engine2, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config())
        engine2.load_checkpoint(str(tmp_path))  # resolves via latest
        assert engine2.global_steps == 2

    def test_elastic_restore_across_zero_stage(self, tmp_path):
        """Save under stage 0 (replicated), restore under stage 3 (sharded) —
        the universal-checkpoint property (reference: elastic_checkpoint +
        checkpoint/universal_checkpoint.py, here by construction)."""
        _, engine = train_losses(ds_config(), steps=3)
        engine.save_checkpoint(str(tmp_path), tag="x")
        ref = [float(engine.train_batch(fixed_batch())["loss"]) for _ in range(2)]

        model = make_model(tiny_cfg())
        cfg3 = ds_config(zero_optimization={"stage": 3})
        engine3, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg3)
        engine3.load_checkpoint(str(tmp_path), tag="x")
        got = [float(engine3.train_batch(fixed_batch())["loss"]) for _ in range(2)]
        np.testing.assert_allclose(ref, got, rtol=2e-4, atol=1e-5)

    def test_save_16bit_model(self, tmp_path):
        _, engine = train_losses(ds_config(), steps=1)
        path = engine.save_16bit_model(str(tmp_path))
        assert os.path.exists(path)


@pytest.mark.slow
class TestOptaxInterop:
    def test_optax_optimizer_drop_in(self):
        optax = pytest.importorskip("optax")
        model = make_model(tiny_cfg())
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, optimizer=optax.adamw(1e-2), config=ds_config())
        batch = fixed_batch()
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(8)]
        assert losses[-1] < losses[0]

    def test_optax_with_zero1_sharding(self):
        optax = pytest.importorskip("optax")
        model = make_model(tiny_cfg())
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, optimizer=optax.sgd(1e-2),
            config=ds_config(zero_optimization={"stage": 1}))
        m = engine.train_batch(fixed_batch())
        assert np.isfinite(float(m["loss"]))


def test_save_load_16bit_roundtrip(tmp_path):
    from deepspeed_tpu.runtime.engine import load_16bit_model
    model = make_model(tiny_cfg(dtype=jnp.bfloat16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=ds_config(bf16={"enabled": True}))
    path = engine.save_16bit_model(str(tmp_path))
    data = load_16bit_model(path)
    key = "tok_embed"
    assert key in data
    assert "bfloat16" in str(data[key].dtype)
    np.testing.assert_array_equal(
        data[key].view(np.uint16),
        np.asarray(engine.state["params"]["tok_embed"]).view(np.uint16))
