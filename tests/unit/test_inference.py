"""KV-cache decode tests.

Reference behavior being matched: the decode workspace + incremental forward
of ``csrc/transformer/inference/includes/inference_context.h`` and
``model_implementations/transformers/ds_transformer.py:18`` — cached decode
must produce the same logits as a full forward over the growing sequence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.engine import InferenceEngine, InferenceConfig
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.models.transformer import forward


def _cfg(**overrides):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, max_seq_len=256, position_type="rotary",
                activation="silu_glu", norm_type="rmsnorm",
                tie_embeddings=False, dtype=jnp.float32,
                attention_impl="xla")
    base.update(overrides)
    return TransformerConfig(**base)


@pytest.mark.parametrize("overrides", [
    {},                                                        # llama-style GQA
    pytest.param({"position_type": "learned", "activation": "gelu",
                  "norm_type": "layernorm", "num_kv_heads": 4,
                  "tie_embeddings": True},
                 marks=pytest.mark.slow),                      # gpt2-style
])
def test_decode_logits_match_full_forward(overrides):
    """prefill + N decode_steps == full forward at every position."""
    cfg = _cfg(**overrides)
    model = make_model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    B, prompt, n_new = 2, 7, 5
    ids = rng.integers(0, cfg.vocab_size, size=(B, prompt + n_new)).astype(np.int32)

    full_logits = forward(params, jnp.asarray(ids), cfg)  # [B, S, V]

    cache = model.init_cache(B, 32, dtype=jnp.float32)
    logits, cache = model.prefill(params, jnp.asarray(ids[:, :prompt]), cache)
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(full_logits[:, prompt - 1]),
                               rtol=1e-4, atol=1e-4)
    for i in range(n_new):
        tok = jnp.asarray(ids[:, prompt + i])
        logits, cache = model.decode_step(params, tok, cache)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full_logits[:, prompt + i]),
            rtol=1e-4, atol=1e-4,
            err_msg=f"decode step {i} diverged from full forward")
    assert int(cache["index"]) == prompt + n_new


def test_prefill_padded_prompt_matches_unpadded():
    """Right-padded prefill (shape bucketing) gives identical logits/cursor."""
    cfg = _cfg()
    model = make_model(cfg)
    params = model.init(jax.random.PRNGKey(1))
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 9)).astype(np.int32)
    padded = np.zeros((2, 16), np.int32)
    padded[:, :9] = ids

    c1 = model.init_cache(2, 32, dtype=jnp.float32)
    l1, c1 = model.prefill(params, jnp.asarray(ids), c1)
    c2 = model.init_cache(2, 32, dtype=jnp.float32)
    l2, c2 = model.prefill(params, jnp.asarray(padded), c2, length=9)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=1e-5,
                               atol=1e-5)
    assert int(c1["index"]) == int(c2["index"]) == 9
    # decode after the padded prefill overwrites pad rows before use
    tok = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2,)))
    d1, _ = model.decode_step(params, tok, c1)
    d2, _ = model.decode_step(params, tok, c2)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-5,
                               atol=1e-5)


@pytest.mark.slow
def test_generate_cached_matches_recompute(devices8):
    """Greedy generate via KV cache == the O(n^2) full-recompute fallback."""
    import dataclasses
    cfg = _cfg()
    model = make_model(cfg)
    eng = InferenceEngine(model, InferenceConfig(tensor_parallel=1,
                                                 dtype=jnp.float32))
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 10)).astype(np.int32)
    out_cached = np.asarray(eng.generate(ids, max_new_tokens=8))

    nocache = dataclasses.replace(model, decode_step=None, init_cache=None)
    eng2 = InferenceEngine(nocache, InferenceConfig(tensor_parallel=1,
                                                    dtype=jnp.float32),
                           params=eng.params)
    out_full = np.asarray(eng2.generate(ids, max_new_tokens=8))
    np.testing.assert_array_equal(out_cached, out_full)
    assert out_cached.shape == (2, 18)


@pytest.mark.slow
def test_generate_tp_sharded(devices8):
    """tensor_parallel=4 decode: cache shards over the tensor axis and the
    generation matches the single-device result."""
    cfg = _cfg(num_heads=4, num_kv_heads=4)
    model = make_model(cfg)
    eng1 = InferenceEngine(model, InferenceConfig(tensor_parallel=1,
                                                  dtype=jnp.float32))
    eng4 = InferenceEngine(model, InferenceConfig(tensor_parallel=4,
                                                  dtype=jnp.float32),
                           params=jax.device_get(eng1.params))
    rng = np.random.default_rng(3)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 6)).astype(np.int32)
    out1 = np.asarray(eng1.generate(ids, max_new_tokens=6))
    out4 = np.asarray(eng4.generate(ids, max_new_tokens=6))
    np.testing.assert_array_equal(out1, out4)


def test_generate_beyond_max_seq_len_raises(devices8):
    cfg = _cfg(max_seq_len=32, position_type="learned", norm_type="layernorm",
               activation="gelu", num_kv_heads=4, tie_embeddings=True)
    model = make_model(cfg)
    eng = InferenceEngine(model, InferenceConfig(dtype=jnp.float32))
    ids = np.ones((1, 20), np.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        eng.generate(ids, max_new_tokens=16)
    out = np.asarray(eng.generate(ids, max_new_tokens=8))  # fits: ok
    assert out.shape == (1, 28)


@pytest.mark.slow
def test_int8_weight_only_inference(devices8):
    """quantize_bits=8: layer weights stored int8 in HBM; logits close to
    full precision, generate works, payloads really are int8."""
    cfg = _cfg(hidden_size=128, num_layers=3)
    model = make_model(cfg)
    eng_fp = InferenceEngine(model, InferenceConfig(dtype=jnp.float32))
    eng_q = InferenceEngine(model, InferenceConfig(dtype=jnp.float32,
                                                   quantize_bits=8),
                            params=jax.device_get(eng_fp.params))
    lay = eng_q.params["layers"]
    wq = lay["wqkv"] if "wqkv" in lay else lay["wq"]  # tp=1 fuses qkv
    assert wq["q"].dtype == jnp.int8
    ids = np.random.default_rng(7).integers(0, cfg.vocab_size,
                                            size=(2, 12)).astype(np.int32)
    lf = np.asarray(eng_fp.forward(ids))
    lq = np.asarray(eng_q.forward(ids))
    # int8 per-channel: small logit error, same top-1 almost everywhere
    assert np.abs(lf - lq).max() < 0.2 * np.abs(lf).max()
    agree = (lf.argmax(-1) == lq.argmax(-1)).mean()
    assert agree > 0.9, agree
    out = np.asarray(eng_q.generate(ids, max_new_tokens=6))
    assert out.shape == (2, 18)


@pytest.mark.slow
def test_generate_temperature_sampling(devices8):
    cfg = _cfg()
    model = make_model(cfg)
    eng = InferenceEngine(model, InferenceConfig(dtype=jnp.float32))
    ids = np.ones((1, 4), np.int32)
    out = np.asarray(eng.generate(ids, max_new_tokens=4, temperature=1.0,
                                  rng=jax.random.PRNGKey(7)))
    assert out.shape == (1, 8)
    assert (out[:, :4] == 1).all()


class TestTwoLevelDecode:
    """Two-level decode (frozen prefix + per-segment suffix carry) engages
    at max_len >= 1024; it must reproduce the single-level scan path —
    same math, different staging (reference analogue: the fixed decode
    workspace of inference_context.h never reallocates in the token loop)."""

    @pytest.mark.slow
    @pytest.mark.parametrize("kv_bits", [0, 8])
    def test_two_level_matches_single_level(self, kv_bits):
        import dataclasses as _dc
        import deepspeed_tpu
        cfg = _cfg(max_seq_len=2048)
        ids = np.random.default_rng(1).integers(0, 128, (2, 950),
                                                dtype=np.int32)
        model = make_model(cfg)
        eng = deepspeed_tpu.init_inference(
            model, config={"kv_cache_bits": kv_bits}, dtype=jnp.float32)
        # pad_prompt 960 + 96 steps -> max_len 1056 >= 1024: two-level path
        out2 = np.asarray(jax.device_get(eng.generate(ids,
                                                      max_new_tokens=80)))
        # strip the suffix hooks to force the single-level scan; the decode
        # loop cache is keyed by shapes only, so it must be cleared
        eng.model = _dc.replace(eng.model, decode_step_suffix=None)
        eng._decode_loop_cache.clear()
        out1 = np.asarray(jax.device_get(eng.generate(ids,
                                                      max_new_tokens=80)))
        assert (out1[:, :950] == out2[:, :950]).all()
        gen1, gen2 = out1[:, 950:], out2[:, 950:]
        # greedy argmax over float32 math: identical up to rare rounding
        # ties; require near-total agreement and an exact first stretch
        assert (gen1[:, :10] == gen2[:, :10]).all(), (gen1, gen2)
        assert (gen1 == gen2).mean() > 0.9, (gen1, gen2)


@pytest.mark.slow
def test_two_level_decode_with_local_windows():
    """The two-level (frozen-prefix + suffix) decode path engages at
    max_len >= 1024; its band masks (prefix valid AND suffix terms) must
    reproduce the full forward for a model with per-layer local windows
    once positions run past the window."""
    import deepspeed_tpu
    cfg = TransformerConfig(
        vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
        max_seq_len=1024, dtype=jnp.float32, attention_impl="xla",
        position_type="learned", attn_windows=(0, 16), qkv_bias=True)
    model = make_model(cfg)
    params = model.init(jax.random.PRNGKey(11))
    eng = deepspeed_tpu.init_inference(model, params=params,
                                       dtype=jnp.float32)
    ids = np.random.default_rng(12).integers(0, 96, (1, 250)).astype(np.int32)
    out = np.asarray(eng.generate(ids, max_new_tokens=20))
    cur = ids
    for _ in range(20):
        logits = np.asarray(forward(params, jnp.asarray(cur), cfg))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out, cur)
