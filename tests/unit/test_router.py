"""Multi-replica serving router (ISSUE 11): registry, spill admission,
circuit breaker, heartbeat-loss failover with in-flight migration.

Most tests drive the REAL ``ServingRouter`` over pure-host stub replicas
(the lint's ``_StubReplica`` — no jax, no devices) with a simulated clock,
so breaker/failover state machines are pinned deterministically and
cheaply. One engine-backed test proves the end-to-end kill -> drain ->
detect -> migrate path produces outputs bit-identical to a fault-free
single-replica run (the full-size version is the slow router chaos soak).
"""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.analysis.serving_lint import (_StubReplica, audit_router,
                                                 main as lint_main,
                                                 simulate_router)
from deepspeed_tpu.inference.router import (BREAKER_CLOSED, BREAKER_DEAD,
                                            BREAKER_OPEN, RouterConfig,
                                            ServingRouter)
from deepspeed_tpu.inference.scheduler import AdmissionRejected
from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.faults import FaultInjector, FaultSchedule


@pytest.fixture(autouse=True)
def _clean_robustness_state():
    rb_faults.clear()
    rb_events.clear()
    yield
    rb_faults.clear()
    rb_events.clear()


def _router(tmp_path, clock, breaker=True, dead_after_s=2.5, **kw):
    cfg = RouterConfig(store_dir=str(tmp_path / "store"),
                       drain_dir=str(tmp_path / "drains"),
                       dead_after_s=dead_after_s, breaker=breaker,
                       breaker_faults=2, breaker_probe_after=2,
                       clock=clock, **kw)
    return ServingRouter(cfg)


def _stubs(router, n=2, **kw):
    c = router.config
    reps = [_StubReplica(f"r{i}", c.store_dir, c.drain_dir, clock=c.clock,
                         **kw) for i in range(n)]
    for rep in reps:
        router.register_handle(rep)
    return reps


class _BoundedStub(_StubReplica):
    """Stub with a queue watermark: sheds typed like a real ServingEngine
    at its admission watermarks."""

    def __init__(self, *a, max_queue=2, **kw):
        super().__init__(*a, **kw)
        self.max_queue = max_queue

    def try_admit(self, prompt, max_new_tokens, rid, **kw):
        if len(self._q) >= self.max_queue:
            raise AdmissionRejected("queue_full", queue_len=len(self._q),
                                    max_queue=self.max_queue)
        return super().try_admit(prompt, max_new_tokens, rid, **kw)


PROMPT = np.arange(4, dtype=np.int32)


class TestHeartbeatMeta:
    """Satellite: schema-versioned heartbeat meta + torn-file skipping
    (the registry substrate the router routes on)."""

    def _rdzv(self, tmp_path, host, t):
        from deepspeed_tpu.elasticity import FileRendezvous
        return FileRendezvous(str(tmp_path), host, dead_after_s=10.0,
                              clock=lambda: t[0])

    def test_meta_roundtrip_schema_versioned(self, tmp_path):
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        b = self._rdzv(tmp_path, "host-b", t)
        a.heartbeat(meta={"queue_depth": 3, "capacity": 8})
        b.heartbeat()                       # new host, no meta: also fine
        info = b.live_host_info()
        assert info["host-a"]["schema"] == 1
        assert info["host-a"]["meta"] == {"queue_depth": 3, "capacity": 8}
        assert "meta" not in info["host-b"]
        assert sorted(info) == a.live_hosts()

    def test_old_schema_hosts_interop(self, tmp_path):
        """A pre-meta host wrote neither schema nor meta — new readers
        must still count it live; old readers only ever looked at
        host/ts, which new payloads still carry."""
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        a.heartbeat(meta={"queue_depth": 1})
        # an old host's payload, written byte-for-byte as PR-6 did
        with open(tmp_path / "hb_host-old.json", "w") as f:
            json.dump({"host": "host-old", "beats": 4, "ts": t[0]}, f)
        info = a.live_host_info()
        assert sorted(info) == ["host-a", "host-old"]
        assert info["host-old"].get("meta") is None
        assert a.live_hosts() == ["host-a", "host-old"]

    def test_torn_heartbeat_skipped_like_tmp_files(self, tmp_path):
        """A torn/unreadable heartbeat payload is skipped exactly like a
        ``.tmp.`` temp — it neither invents a host nor kills the reader."""
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        a.heartbeat(meta={"queue_depth": 0})
        with open(tmp_path / "hb_host-torn.json", "w") as f:
            f.write('{"host": "host-torn", "beats": 2, "ts"')   # torn
        with open(tmp_path / "hb_host-c.json.tmp.999", "w") as f:
            json.dump({"host": "host-c", "beats": 1, "ts": t[0]}, f)
        assert a.live_hosts() == ["host-a"]
        assert sorted(a.read_heartbeats()) == ["host-a"]


class TestSpillAdmission:
    def test_spills_to_sibling_instead_of_shedding(self, tmp_path):
        """A watermark shed on the least-loaded choice lands on the next
        sibling (typed + evented), never surfaces to the caller."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        c = router.config
        reps = [_BoundedStub(f"r{i}", c.store_dir, c.drain_dir, max_queue=2,
                             clock=c.clock) for i in range(2)]
        for rep in reps:
            router.register_handle(rep)
        for _ in range(4):               # r0 fills (2), then spills (2)
            router.add_request(PROMPT, 8)
        assert reps[0].inflight() == 2 and reps[1].inflight() == 2
        st = router.stats()
        assert st["spilled"] == 2.0 and st["shed"] == 0.0
        assert rb_events.history("request_spilled")
        assert st["spill_rate"] == 0.5

    def test_all_saturated_is_a_typed_shed(self, tmp_path):
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        c = router.config
        for i in range(2):
            router.register_handle(
                _BoundedStub(f"r{i}", c.store_dir, c.drain_dir, max_queue=1,
                             clock=c.clock))
        router.add_request(PROMPT, 8)
        router.add_request(PROMPT, 8)
        with pytest.raises(AdmissionRejected) as ei:
            router.add_request(PROMPT, 8)
        assert ei.value.reason == "all_replicas_saturated"
        assert ei.value.detail["healthy"] == 2
        st = router.stats()
        assert st["shed"] == 1.0
        assert any(e.get("reason") == "all_replicas_saturated"
                   for e in rb_events.history("request_shed"))

    def test_least_loaded_wins(self, tmp_path):
        """Admission ranks by registry meta (queue+running over capacity):
        a loaded replica loses to an idle one even when registered first.
        The registry cache refreshes once per routing round (replicas
        publish at round boundaries), so the load shows up after a step."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        r0, r1 = _stubs(router, 2, service_rate=0)
        router.add_request(PROMPT, 8)            # tie -> r0 (registration)
        router.step()                            # boundary: meta republished
        t[0] += 1.0
        router.add_request(PROMPT, 8)            # r1 now least loaded
        assert r0.inflight() == 1 and r1.inflight() == 1


class TestCircuitBreaker:
    def test_heartbeat_loss_opens_then_half_open_probe_recovers(
            self, tmp_path):
        """A live-but-silent replica degrades (breaker OPEN, no new
        admissions) and recovers through the half-open probe once its
        heartbeats return — never a migration (fencing: no death
        evidence)."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        r0, r1 = _stubs(router, 2)
        rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "heartbeat_loss", "at": 1, "replica": 0, "times": 4},
        ], seed=0)))
        opened_round = closed_round = None
        for rnd in range(12):
            router.step()
            t[0] += 1.0
            state = router.breaker_state("r0")
            if opened_round is None and state == BREAKER_OPEN:
                opened_round = rnd
                # OPEN replica takes no new admissions
                router.add_request(PROMPT, 8)
                assert r0.inflight() == 0 and r1.inflight() == 1
            if opened_round is not None and closed_round is None \
                    and state == BREAKER_CLOSED:
                closed_round = rnd
        assert opened_round is not None, "breaker never opened"
        assert closed_round is not None, "breaker never closed again"
        assert [e["reason"] for e in
                rb_events.history("replica_degraded")] == ["heartbeat_loss"]
        assert rb_events.history("replica_recovered")
        # fencing: alive + silent is a partition, not a death
        assert not rb_events.history("request_migrated")
        assert router.stats()["failovers"] == 0.0

    def test_partition_opens_on_dispatch_faults_and_manifest_fallback(
            self, tmp_path):
        """A router_partition raises on dispatch (consecutive faults open
        the breaker) and tears the newest generation manifest — the
        registry's generation reads survive via the torn-newest fallback
        and the post-heal publish continues the history (never gen 0)."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        _stubs(router, 2)
        gen_before = router.generation()["generation"]
        rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "router_partition", "at": 1, "replica": 0, "times": 3},
        ], seed=0)))
        reps = list(router.replicas.values())
        for rnd in range(10):
            router.step()
            if rnd == 1:
                # mid-partition: r0 is known-unreachable this round — an
                # admission must NOT be routed into the partition on its
                # frozen low-load meta (it lands on r1 instead)
                router.add_request(PROMPT, 8)
                assert reps[0].inflight() == 0
                assert reps[1].inflight() >= 1
            t[0] += 1.0
        degraded = rb_events.history("replica_degraded")
        assert [e["reason"] for e in degraded] == ["dispatch_faults"]
        assert rb_events.history("replica_recovered")
        assert router.breaker_state("r0") == BREAKER_CLOSED
        # the torn gen_<N+1>.json exists on disk, yet generation reads
        # fell back and the history is monotone past it
        store = router.config.store_dir
        torn = [fn for fn in os.listdir(store) if fn.startswith("gen_")
                and not _readable_json(os.path.join(store, fn))]
        assert torn, "the partition never tore a manifest"
        cur = router.generation()
        assert cur is not None and cur["generation"] >= gen_before
        # a post-heal membership publish continues the chain
        router._publish_generation()
        assert router.generation()["generation"] > gen_before

    def test_fault_schedule_validates_router_kinds(self):
        with pytest.raises(ValueError, match="'at'"):
            FaultSchedule([{"kind": "replica_kill", "replica": 1}])
        with pytest.raises(ValueError, match="'replica'"):
            FaultSchedule([{"kind": "heartbeat_loss", "at": 2}])
        ok = FaultSchedule([{"kind": "router_partition", "at": 0,
                             "replica": 0, "times": 2}])
        assert ok.entries[0]["times"] == 2


def _readable_json(path):
    try:
        with open(path) as f:
            json.load(f)
        return True
    except ValueError:
        return False


class TestFailover:
    def test_drained_kill_migrates_snapshot_to_survivor(self, tmp_path):
        """Supervised kill: drain snapshot through the integrity chain,
        heartbeat-loss detection, per-request migration onto the
        survivor; nothing lost, membership generation re-published."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        r0, r1 = _stubs(router, 2, service_rate=0)
        for _ in range(3):
            router.add_request(PROMPT, 8)       # all tie-break onto r0
        r0.publish()
        assert router.replica_inflight() == {"r0": 3, "r1": 0}
        r0.die()                                # drain + silence
        for _ in range(5):
            router.step()
            t[0] += 1.0
        st = router.stats()
        assert st["failovers"] == 1.0 and st["migrated"] == 3.0
        assert st["lost_requests"] == 0.0 and st["resubmitted"] == 0.0
        assert router.replica_inflight() == {"r0": 0, "r1": 3}
        assert router.breaker_state("r0") == BREAKER_DEAD
        migrated = rb_events.history("request_migrated")
        assert len(migrated) == 3
        assert all(e["src"] == "r0" and e["dst"] == "r1"
                   and e["origin"] == "drain" for e in migrated)
        assert rb_events.history("replica_failover")
        # the dead replica left the membership manifest
        assert router.generation()["hosts"] == ["r1"]
        # and admissions never consider it again
        router.add_request(PROMPT, 8)
        assert router.replica_inflight()["r1"] == 4

    def test_preexisting_snapshot_is_not_death_evidence(self, tmp_path):
        """Fencing regression: a drain snapshot left over from a previous
        incarnation (present BEFORE registration) must not convert a
        transient heartbeat blip into a false failover — the live
        replica's work would be double-served."""
        from deepspeed_tpu.robustness import integrity
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        c = router.config
        # a previous incident's committed drain, already on disk
        old = os.path.join(c.drain_dir, "r0", "drain_r0")
        os.makedirs(old)
        integrity.atomic_write(os.path.join(old, "state.json"),
                               json.dumps({"version": 2, "requests": [
                                   {"rid": 999, "prompt": [1, 2],
                                    "max_new_tokens": 4,
                                    "generated": []}]}),
                               what="stale drain")
        integrity.write_manifest(old)
        integrity.write_commit_marker(old)
        router.register_handle(_StubReplica("r0", c.store_dir, c.drain_dir,
                                            clock=c.clock))
        router.register_handle(_StubReplica("r1", c.store_dir, c.drain_dir,
                                            clock=c.clock))
        router.add_request(PROMPT, 8)
        # heartbeat blip on the LIVE replica: breaker opens, then heals —
        # the stale snapshot must never trigger a failover
        rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "heartbeat_loss", "at": 1, "replica": 0, "times": 4},
        ], seed=0)))
        for _ in range(12):
            router.step()
            t[0] += 1.0
        st = router.stats()
        assert st["failovers"] == 0.0 and st["migrated"] == 0.0, st
        assert not rb_events.history("request_migrated")
        assert router.breaker_state("r0") == BREAKER_CLOSED
        assert st["completed"] == 1.0     # the live replica kept serving

    def test_failover_consumes_the_snapshot(self, tmp_path):
        """A migrated snapshot is invalidated (COMMITTED dropped, payload
        kept for post-mortems): it can never be resumed or count as death
        evidence twice."""
        from deepspeed_tpu.robustness import integrity
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        r0, r1 = _stubs(router, 2, service_rate=0)
        router.add_request(PROMPT, 8)
        r0.publish()
        r0.die()
        for _ in range(5):
            router.step()
            t[0] += 1.0
        assert router.stats()["failovers"] == 1.0
        tag_dir = os.path.join(r0.drain_dir, "drain_r0")
        assert not integrity.is_committed(tag_dir)        # consumed
        assert os.path.exists(os.path.join(tag_dir, "state.json"))

    def test_lost_requests_survive_as_committed_residue(self, tmp_path):
        """When no survivor can hold a drained request, the failover must
        NOT destroy its only durable copy: the snapshot is rewritten to
        hold exactly the lost records, still integrity-committed, so an
        operator with a large-enough engine can resume them later — while
        this router treats the residue as consumed evidence (no
        re-failover loop)."""
        from deepspeed_tpu.inference.serving import (ResumeIncompatible,
                                                     load_drain_state)
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        c = router.config

        class _SmallStub(_StubReplica):
            def accept_migration(self, recs, rng_counter=None,
                                 source=None, geometry=None):
                if any(int(r["rid"]) == 1 for r in recs):
                    raise ResumeIncompatible("request 1 exceeds this "
                                             "engine's max_model_len")
                return super().accept_migration(recs, rng_counter,
                                                source)

        r0 = _StubReplica("r0", c.store_dir, c.drain_dir, clock=c.clock,
                          service_rate=0)
        r1 = _SmallStub("r1", c.store_dir, c.drain_dir, clock=c.clock)
        router.register_handle(r0)
        router.register_handle(r1)
        router.add_request(PROMPT, 8)          # rid 0: fits the survivor
        router.add_request(PROMPT, 8)          # rid 1: too big for it
        r0.publish()
        r0.die()
        for _ in range(8):
            router.step()
            t[0] += 1.0
        st = router.stats()
        assert st["failovers"] == 1.0          # exactly one episode
        assert st["migrated"] == 1.0 and st["lost_requests"] == 1.0
        residue = load_drain_state(os.path.join(c.drain_dir, "r0"))
        assert residue.get("failover_residue") is True
        assert [r["rid"] for r in residue["requests"]] == [1]
        # the residue keeps the ORIGINAL drained geometry: a later
        # whole-drain resume still hits the v2 envelope check
        assert residue["engine"]["max_model_len"] == 4096

    def test_corrupt_snapshot_falls_back_to_resubmit(self, tmp_path):
        """A snapshot that passes the shallow evidence check but fails the
        deep checksum (size-preserving bitrot — the corrupt_payload
        class) must NOT wedge the failover: the router falls back to
        resubmitting its own admission records, nothing is stranded, and
        the bad tag becomes consumed evidence."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        r0, r1 = _stubs(router, 2, service_rate=0)
        for _ in range(2):
            router.add_request(PROMPT, 8)
        r0.publish()
        r0.die()
        # size-preserving corruption of the drained state
        state_path = os.path.join(r0.drain_dir, "drain_r0", "state.json")
        raw = bytearray(open(state_path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(state_path, "wb") as f:
            f.write(bytes(raw))
        for _ in range(6):
            router.step()
            t[0] += 1.0
        st = router.stats()
        assert st["failovers"] == 1.0, st
        assert st["migrated"] == 2.0 and st["resubmitted"] == 2.0, st
        assert st["lost_requests"] == 0.0
        assert router.replica_inflight() == {"r0": 0, "r1": 2}
        assert rb_events.history("drain_snapshot_invalid")
        assert all(e["origin"] == "resubmit"
                   for e in rb_events.history("request_migrated"))

    def test_too_long_request_spills_to_larger_replica(self, tmp_path):
        """Heterogeneous geometry: a request that exceeds the least-loaded
        replica's context cap spills (typed) to a sibling that can hold
        it; one no replica can EVER hold sheds permanently ("too_long"),
        never crashes the caller or spins run() forever."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        c = router.config

        class _CappedStub(_StubReplica):
            def __init__(self, *a, max_model_len=64, **kw):
                super().__init__(*a, **kw)
                self.max_model_len = max_model_len

            def try_admit(self, prompt, max_new_tokens, rid, **kw):
                if len(prompt) + max_new_tokens > self.max_model_len:
                    raise AdmissionRejected(
                        "too_long", replica=self.name,
                        max_model_len=self.max_model_len)
                return super().try_admit(prompt, max_new_tokens, rid,
                                         **kw)

        small = _CappedStub("r0", c.store_dir, c.drain_dir, clock=c.clock,
                            max_model_len=32)
        big = _CappedStub("r1", c.store_dir, c.drain_dir, clock=c.clock,
                          max_model_len=128)
        router.register_handle(small)
        router.register_handle(big)
        rid = router.add_request(np.arange(20, dtype=np.int32), 30)
        assert router._placement[rid] == "r1"     # spilled, not crashed
        assert router.stats()["spilled"] == 1.0
        with pytest.raises(AdmissionRejected) as ei:
            router.add_request(np.arange(120, dtype=np.int32), 30)
        assert ei.value.reason == "too_long"      # permanent, typed

    def test_heartbeat_write_failure_does_not_drop_finished_work(
            self, tmp_path):
        """A transient store-write failure publishing the heartbeat must
        not discard the round's completed requests — the missed beat just
        ages the heartbeat (the health signal), the work surfaces."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        r0, = _stubs(router, 1)
        rid = router.add_request(PROMPT, 8)

        def failing_heartbeat(meta=None):
            raise OSError("injected EIO writing hb_r0.json")
        r0.rdzv.heartbeat = failing_heartbeat
        finished = []
        for _ in range(4):
            finished += router.step()
            t[0] += 1.0
        assert any(f.rid == rid for f in finished), \
            "completed work was dropped with the failed heartbeat"
        assert router.replica_inflight()["r0"] == 0

    def test_engine_handle_types_the_context_cap_refusal(self, tmp_path):
        """The engine-backed ReplicaHandle pre-checks the context cap and
        raises the TYPED AdmissionRejected — ServingEngine.add_request
        alone raises an untyped ValueError (a caller bug when talking to
        one engine; a routing signal under a heterogeneous router)."""
        import jax.numpy as jnp
        import deepspeed_tpu
        from deepspeed_tpu.inference.router import ReplicaHandle
        from deepspeed_tpu.models import TransformerConfig, make_model
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
            num_kv_heads=2, max_seq_len=32, position_type="rotary",
            activation="silu_glu", norm_type="rmsnorm",
            tie_embeddings=False, dtype=jnp.float32,
            attention_impl="xla"))
        srv = deepspeed_tpu.init_serving(model, config={}, serving=dict(
            max_seqs=1, block_size=16, max_model_len=32,
            prompt_bucket=16, decode_backend="xla"), dtype=jnp.float32)
        h = ReplicaHandle("rx", srv, str(tmp_path / "store"),
                          str(tmp_path / "drains"))
        with pytest.raises(AdmissionRejected) as ei:
            h.try_admit(np.arange(30, dtype=np.int32), 30, rid=99)
        assert ei.value.reason == "too_long"
        assert ei.value.detail["max_model_len"] == 32
        # and the engine-backed step() guards the heartbeat publish: a
        # store-write failure must not drop the round's finished work
        h.try_admit(np.arange(6, dtype=np.int32), 3, rid=0)

        def failing_heartbeat(meta=None):
            raise OSError("injected EIO")
        h.rdzv.heartbeat = failing_heartbeat
        finished = []
        for _ in range(8):
            finished += h.step()
            if finished:
                break
        assert [r.rid for r in finished] == [0]

    def test_silent_death_without_snapshot_resubmits_from_records(
            self, tmp_path):
        """Hard crash (no drain): once death is confirmed, the router
        resubmits its own admission records from scratch — full
        regeneration, zero lost requests."""
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        r0, r1 = _stubs(router, 2, service_rate=0)
        for _ in range(2):
            router.add_request(PROMPT, 8)
        r0.silent = True                        # crash: no drain written
        r0.dead = True                          # confirmed out-of-band
        for _ in range(5):
            router.step()
            t[0] += 1.0
        st = router.stats()
        assert st["migrated"] == 2.0 and st["resubmitted"] == 2.0
        assert st["lost_requests"] == 0.0
        assert router.replica_inflight() == {"r0": 0, "r1": 2}
        assert all(e["origin"] == "resubmit"
                   for e in rb_events.history("request_migrated"))


class TestRouterBlackholeCorpus:
    def test_defect_fires_inflight_growth(self):
        report = audit_router(breaker=False)
        assert not report.ok
        assert [f.rule for f in report.findings] == ["inflight-growth"]
        sim = report.meta
        post = sim["inflight_r0"][sim["kill_round"]:]
        assert all(b >= a for a, b in zip(post, post[1:]))
        assert sim["survivor_completed"] == 0   # every request blackholed

    def test_breaker_twin_fails_over_and_passes(self):
        report = audit_router(breaker=True)
        assert report.ok, [f.rule for f in report.findings]
        assert report.meta["migrated"] > 0
        assert report.meta["lost"] == 0
        # the survivor served the migrated work AND the later arrivals
        assert report.meta["survivor_completed"] > 0

    def test_corpus_entry_registered(self):
        from deepspeed_tpu.analysis.corpus import run_corpus
        assert not run_corpus("router-blackhole").ok

    def test_cli_both_directions(self, capsys):
        assert lint_main(["--router"]) == 1
        assert "inflight-growth" in capsys.readouterr().out
        assert lint_main(["--router", "--breaker"]) == 0
        assert "OK" in capsys.readouterr().out

    def test_simulation_is_deterministic(self):
        a = simulate_router(breaker=False, rounds=16)
        b = simulate_router(breaker=False, rounds=16)
        assert a["inflight_r0"] == b["inflight_r0"]


class TestEngineBackedFailover:
    def test_kill_failover_bit_identical_to_single_replica(self, tmp_path):
        """End-to-end on real ServingEngines: a replica_kill mid-load
        drains through the integrity chain, the router detects the
        heartbeat loss and migrates the snapshot onto the survivor, and
        every output is bit-identical to a fault-free single-replica run
        (the slow router chaos soak scales this to 30+ rounds with
        partitions and spill storms)."""
        import jax
        import jax.numpy as jnp
        import deepspeed_tpu
        from deepspeed_tpu.models import TransformerConfig, make_model

        model = make_model(TransformerConfig(
            vocab_size=128, hidden_size=64, num_layers=1, num_heads=4,
            num_kv_heads=2, max_seq_len=64, position_type="rotary",
            activation="silu_glu", norm_type="rmsnorm",
            tie_embeddings=False, dtype=jnp.float32,
            attention_impl="xla"))
        params = jax.device_get(model.init(jax.random.PRNGKey(0)))

        def serving(**kw):
            d = dict(max_seqs=2, block_size=16, max_model_len=64,
                     decode_quantum=2, prompt_bucket=16,
                     decode_backend="xla", max_queue=4)
            d.update(kw)
            return deepspeed_tpu.init_serving(
                model, config={}, serving=d, dtype=jnp.float32,
                params=params)

        rng = np.random.default_rng(3)
        reqs = [(rng.integers(0, 128, size=(int(n),)).astype(np.int32),
                 int(k))
                for n, k in zip(rng.integers(4, 16, 6),
                                rng.integers(4, 8, 6))]
        base = serving(max_seqs=4, max_queue=None).run(list(reqs))

        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0], dead_after_s=2.0)
        router.register("r0", serving())
        router.register("r1", serving())
        # replica 0 holds the work (admission ties break toward it), so
        # killing IT guarantees a non-empty drain snapshot to migrate
        rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "replica_kill", "at": 2, "replica": 0},
        ], seed=0)))
        import collections
        pending = collections.deque(reqs)
        outs, rounds = {}, 0
        while pending or not router.done:
            while pending:
                p, k = pending[0]
                try:
                    router.add_request(p, k)
                except AdmissionRejected:
                    break
                pending.popleft()
            for r in router.step():
                outs[r.rid] = r.output
            t[0] += 1.0
            rounds += 1
            assert rounds < 200, "router test did not converge"
        st = router.stats()
        assert st["lost_requests"] == 0.0
        assert st["failovers"] == 1.0 and st["migrated"] >= 1.0
        assert set(outs) == set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged across replicas")
