"""ZeRO-Infinity layer-streamed executor (params + opt state on NVMe).

Reference test model: the reference validates its swappers with parity tests
against in-memory optimizers (tests/unit/runtime/zero, tests/unit/ops/aio);
here the layer-streamed step is checked against a monolithic jax
implementation running on the SAME weights read back from the chunk store.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import llama_config
from deepspeed_tpu.models.transformer import make_model

# quick tier: `pytest -m 'not slow'` skips this module (layer-streamed executor suites re-init multi-hundred-MB stores)
pytestmark = pytest.mark.slow


def _cfg_dict(tmp, gas=1, clip=0.0):
    return {
        "train_batch_size": 4 * gas,
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "gradient_clipping": clip,
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "nvme", "nvme_path": str(tmp)},
            "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp)},
        },
        "steps_per_print": 1000000,
    }


def _model():
    return make_model(llama_config("tiny", max_seq_len=128, loss_chunk=64),
                      name="tiny")


def _batch(B=4, S=128, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 32000, (B, S), dtype=np.int32)}


def _gather_stacked(ex):
    """Assemble the stacked params tree from the executor's chunk store."""
    import ml_dtypes
    cfg = ex.cfg
    L = cfg.num_layers
    layers = []
    for i in range(L):
        bits = ex.store.read_param(i)
        flat = bits.view(ml_dtypes.bfloat16).astype(np.float32)
        leaves, off = [], 0
        for size, shape in zip(ex._sizes, ex._shapes):
            leaves.append(flat[off:off + size].reshape(shape))
            off += size
        layers.append(jax.tree.unflatten(ex._treedef, leaves))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *layers)
    params = {k: jax.tree.map(jnp.asarray, v)
              for k, v in jax.device_get(ex.nl_params).items()}
    params["layers"] = jax.tree.map(lambda a: a.astype(jnp.bfloat16), stacked)
    return params


class TestInfinityExecutor:
    def test_step_parity_vs_monolithic(self, tmp_path):
        """One layer-streamed train step == monolithic forward/grad/AdamW on
        the same weights (fwd loss, grad norm, and updated master chunks)."""
        model = _model()
        engine, *_ = deepspeed_tpu.initialize(model=model,
                                              config=_cfg_dict(tmp_path))
        ex = engine._infinity_exec
        cfg = ex.cfg
        params = _gather_stacked(ex)
        batch = _batch()

        # monolithic reference: same math, stacked scan
        from deepspeed_tpu.models.transformer import lm_loss
        ref_cfg = cfg.__class__(**{**cfg.__dict__, "scan_layers": True})

        def loss_fn(p):
            return lm_loss(p, {"input_ids": jnp.asarray(batch["input_ids"])},
                           ref_cfg, deterministic=True)

        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params)

        metrics = engine.train_batch(batch)
        got_loss = float(metrics["loss"])
        assert abs(got_loss - float(ref_loss)) < 3e-2, \
            (got_loss, float(ref_loss))

        # grad norm parity (fp32 reference norm; bf16 kernels -> loose tol)
        ref_norm = math.sqrt(sum(
            float(jnp.sum(g.astype(jnp.float32) ** 2))
            for g in jax.tree.leaves(ref_grads)))
        got_norm = float(metrics["grad_norm"])
        assert abs(got_norm - ref_norm) / max(ref_norm, 1e-6) < 0.1, \
            (got_norm, ref_norm)

        # AdamW parity on layer 0's master chunk
        opt0 = ex.store.read_opt(0)
        assert opt0 is not None
        l0_flat = np.concatenate([
            np.asarray(v, np.float32).reshape(-1)
            for v in jax.tree.leaves(
                jax.tree.map(lambda a: a[0], params["layers"]))])
        g0_flat = np.concatenate([
            np.asarray(g.astype(jnp.float32))[0].reshape(-1)
            for g in jax.tree.leaves(ref_grads["layers"])])
        lr, b1, b2, eps, wd = 1e-3, 0.9, 0.999, 1e-8, 0.01
        m = (1 - b1) * g0_flat
        v = (1 - b2) * g0_flat * g0_flat
        upd = (m / (1 - b1)) / (np.sqrt(v / (1 - b2)) + eps) + wd * l0_flat
        expect_master = l0_flat - lr * upd
        got_master = opt0[0][:expect_master.size]
        err = np.max(np.abs(got_master - expect_master))
        assert err < 5e-3, err
        engine._infinity_exec.close()

    def test_loss_decreases_and_eval(self, tmp_path):
        model = _model()
        engine, *_ = deepspeed_tpu.initialize(model=model,
                                              config=_cfg_dict(tmp_path))
        batch = _batch()
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(8)]
        assert losses[-1] < losses[0], losses
        ev = float(engine.eval_batch(batch))
        assert np.isfinite(ev)
        engine._infinity_exec.close()

    def test_measure_decomposition_reports_positive_times(self, tmp_path):
        """The capacity rung's transfer-vs-compute decomposition (bench.py
        emits it as offload_dma_ms/offload_compute_ms + overlap fraction):
        both probes measure real work and the per-step scaling is 2L chunk
        DMAs (fwd+bwd fetch) x L layer fwd+bwd computations."""
        engine, *_ = deepspeed_tpu.initialize(model=_model(),
                                              config=_cfg_dict(tmp_path))
        batch = _batch()
        engine.train_batch(batch)   # compile + populate the store
        d = engine._infinity_exec.measure_decomposition(batch, reps=1)
        for k in ("offload_chunk_dma_ms", "offload_layer_ms",
                  "offload_dma_ms", "offload_compute_ms"):
            assert d[k] > 0, d
        L = engine._infinity_exec.cfg.num_layers
        assert d["offload_dma_ms"] == pytest.approx(
            d["offload_chunk_dma_ms"] * 2 * L, rel=0.02, abs=0.1)
        assert d["offload_compute_ms"] == pytest.approx(
            d["offload_layer_ms"] * L, rel=0.02, abs=0.1)
        engine._infinity_exec.close()

    def test_grad_accumulation(self, tmp_path):
        model = _model()
        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=_cfg_dict(tmp_path, gas=2))
        batch = _batch(B=8)
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(5)]
        assert losses[-1] < losses[0], losses
        engine._infinity_exec.close()

    def test_checkpoint_roundtrip(self, tmp_path):
        model = _model()
        cfgd = _cfg_dict(tmp_path / "swap")
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfgd)
        batch = _batch()
        for _ in range(3):
            engine.train_batch(batch)
        l_before = float(engine.eval_batch(batch))
        path = engine.save_checkpoint(str(tmp_path / "ckpt"))
        assert path

        engine2, *_ = deepspeed_tpu.initialize(
            model=_model(), config=_cfg_dict(tmp_path / "swap2"))
        engine2.load_checkpoint(str(tmp_path / "ckpt"))
        l_after = float(engine2.eval_batch(batch))
        assert abs(l_before - l_after) < 1e-3, (l_before, l_after)
        # resumed training continues down
        l_next = float(engine2.train_batch(batch)["loss"])
        assert l_next < l_before + 0.1
        engine._infinity_exec.close()
        engine2._infinity_exec.close()

    def test_clip_applied(self, tmp_path):
        model = _model()
        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=_cfg_dict(tmp_path, clip=0.01))
        m = engine.train_batch(_batch())
        assert float(m["grad_norm"]) > 0
        engine._infinity_exec.close()

    def test_cpu_cpu_routes_to_executor(self, tmp_path):
        """offload_param=cpu + offload_optimizer=cpu -> layer-streamed
        executor on the host tier (pinned TPU-host DRAM on hardware)."""
        cfg = _cfg_dict(tmp_path)
        cfg["zero_optimization"]["offload_param"] = {"device": "cpu"}
        cfg["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
        assert engine._infinity and engine._infinity_backend == "host"
        batch = _batch()
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(5)]
        assert losses[-1] < losses[0], losses
        engine._infinity_exec.close()

    def test_validation_errors(self, tmp_path):
        model = _model()
        cfg = _cfg_dict(tmp_path)
        cfg["zero_optimization"]["offload_param"]["nvme_path"] = None
        cfg["zero_optimization"]["offload_optimizer"] = {"device": "none"}
        with pytest.raises(Exception, match="nvme_path"):
            deepspeed_tpu.initialize(model=model, config=cfg)
        cfg2 = _cfg_dict(tmp_path)
        cfg2["optimizer"] = {"type": "sgd", "params": {"lr": 1e-3}}
        with pytest.raises(Exception, match="Adam"):
            deepspeed_tpu.initialize(model=model, config=cfg2)


class TestInfinityMultiChip:
    """Offload composed with data/fsdp parallelism (reference: ZeRO-3 + NVMe
    at 512 GPUs — stage3.py:65 + partitioned_param_swapper.py:35). Layer
    chunks shard over fsdp; the loss trajectory must match the single-device
    executor on the same global batch up to reduction order."""

    def _losses(self, tmp, mesh_axes, devices, steps=3, gas=1,
                global_mb=16):
        dp = 1
        for v in (mesh_axes or {}).values():
            dp *= v
        cfg = _cfg_dict(tmp, gas=gas)
        cfg["train_batch_size"] = global_mb * gas
        cfg["train_micro_batch_size_per_gpu"] = global_mb // dp
        if mesh_axes:
            cfg["mesh"] = {"axes": mesh_axes}
        engine, *_ = deepspeed_tpu.initialize(
            model=_model(), config=cfg, devices=devices)
        if mesh_axes:
            assert engine._infinity_multi
            assert engine._infinity_exec.dp == dp
        batch = _batch(B=cfg["train_batch_size"])
        out = [float(engine.train_batch(batch)["loss"])
               for _ in range(steps)]
        engine._infinity_exec.close()
        return out

    def test_fsdp4_parity_vs_single_device(self, tmp_path, devices8):
        ref = self._losses(tmp_path / "ref", None, [devices8[0]])
        got = self._losses(tmp_path / "fsdp", {"fsdp": 4}, devices8[:4])
        np.testing.assert_allclose(got, ref, rtol=3e-3)

    def test_data2_fsdp2_gas2_trains(self, tmp_path, devices8):
        losses = self._losses(tmp_path / "mix", {"data": 2, "fsdp": 2},
                              devices8[:4], steps=4, gas=2)
        assert losses[-1] < losses[0], losses

    def test_fsdp2_tensor2_parity_vs_single_device(self, tmp_path,
                                                   devices8):
        """Offload composed with the TENSOR axis (r4 verdict missing #1:
        the reference runs ZeRO-3+NVMe under a Megatron TP mpu,
        engine.py:1088-1100 + stage3.py:65). Chunks shard over
        fsdp x tensor; the per-layer jits re-shard weights to col/row
        specs, so the tensor axis carries compute, and the loss must
        match the single-device executor."""
        ref = self._losses(tmp_path / "ref", None, [devices8[0]])
        cfg = _cfg_dict(tmp_path / "tp")
        cfg["train_batch_size"] = 16
        cfg["train_micro_batch_size_per_gpu"] = 8   # dp = data*fsdp = 2
        cfg["mesh"] = {"axes": {"fsdp": 2, "tensor": 2}}
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg,
                                              devices=devices8[:4])
        assert engine._infinity_multi
        assert engine._infinity_exec._TP == 2
        assert engine._infinity_exec.dp == 2
        batch = _batch(B=16)
        got = [float(engine.train_batch(batch)["loss"]) for _ in range(3)]
        engine._infinity_exec.close()
        np.testing.assert_allclose(got, ref, rtol=3e-3)

    def test_pipe_axis_rejected(self, tmp_path, devices8):
        cfg = _cfg_dict(tmp_path)
        cfg["train_batch_size"] = 8
        cfg["mesh"] = {"axes": {"pipe": 2, "fsdp": 2}}
        with pytest.raises(Exception, match="pipe"):
            deepspeed_tpu.initialize(model=_model(), config=cfg,
                                     devices=devices8[:4])

    def test_checkpoint_across_fsdp_degree(self, tmp_path, devices8):
        """Save on fsdp=4 (chunk aligned to 512), restore single-device
        (chunk aligned 128): the zero-pad region re-chunks, losses continue."""
        dp = 4
        cfg = _cfg_dict(tmp_path / "w")
        cfg["train_batch_size"] = 16
        cfg["train_micro_batch_size_per_gpu"] = 4
        cfg["mesh"] = {"axes": {"fsdp": dp}}
        e1, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg,
                                          devices=devices8[:4])
        batch = _batch(B=16)
        first = [float(e1.train_batch(batch)["loss"]) for _ in range(2)]
        e1.save_checkpoint(str(tmp_path / "ck"))
        e1._infinity_exec.close()

        cfg2 = _cfg_dict(tmp_path / "r")
        cfg2["train_batch_size"] = 16
        cfg2["train_micro_batch_size_per_gpu"] = 16
        e2, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg2,
                                          devices=[devices8[0]])
        e2.load_checkpoint(str(tmp_path / "ck"))
        cont = float(e2.train_batch(batch)["loss"])
        e2._infinity_exec.close()
        assert cont < first[0], (cont, first)


class TestInfinityFp16Compression:
    """VERDICT r3 item 7: fp16 x offload and compression x offload compose
    (reference composes fp16 with every offload mode)."""

    def test_fp16_trains_and_scale_tracks(self, tmp_path):
        cfg = _cfg_dict(tmp_path)
        cfg.pop("bf16")
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
        assert engine._infinity and engine._infinity_exec.fp16
        batch = _batch()
        ms = [engine.train_batch(batch) for _ in range(6)]
        losses = [float(m["loss"]) for m in ms]
        assert losses[-1] < losses[0], losses
        assert float(ms[-1]["loss_scale"]) == 2.0 ** 8
        engine._infinity_exec.close()

    def test_fp16_overflow_skips_and_shrinks(self, tmp_path):
        cfg = _cfg_dict(tmp_path)
        cfg.pop("bf16")
        # scale 2^40 guarantees inf fp16 grads -> overflow path
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 40,
                       "hysteresis": 1}
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
        ex = engine._infinity_exec
        batch = _batch()
        m = engine.train_batch(batch)
        assert bool(m["overflow"])
        assert ex._scale < 2.0 ** 40      # shrank
        assert ex.applied_steps == 0      # step skipped
        # keep training: the scale walks down until steps apply
        for _ in range(30):
            m = engine.train_batch(batch)
            if not bool(m["overflow"]):
                break
        assert ex.applied_steps >= 1
        engine._infinity_exec.close()

    def test_fp16_checkpoint_keeps_scale(self, tmp_path):
        cfg = _cfg_dict(tmp_path / "a")
        cfg.pop("bf16")
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 10,
                       "hysteresis": 1}
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
        batch = _batch()
        engine.train_batch(batch)
        engine._infinity_exec._scale = 128.0  # distinctive value
        engine.save_checkpoint(str(tmp_path / "ck"))
        cfg2 = _cfg_dict(tmp_path / "b")
        cfg2.pop("bf16")
        cfg2["fp16"] = {"enabled": True, "initial_scale_power": 10,
                        "hysteresis": 1}
        e2, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg2)
        e2.load_checkpoint(str(tmp_path / "ck"))
        assert e2._infinity_exec._scale == 128.0
        engine._infinity_exec.close()
        e2._infinity_exec.close()

    def test_compression_weight_quant_composes(self, tmp_path):
        cfg = _cfg_dict(tmp_path)
        cfg["compression_training"] = {
            "weight_quantization": {
                "shared_parameters": {"enabled": True,
                                      "quantizer_kernel": False,
                                      "schedule_offset": 0,
                                      "quantize_groups": 1,
                                      "quantize_verbose": False,
                                      "quantization_type": "symmetric",
                                      "quantize_weight_in_forward": True,
                                      "rounding": "nearest",
                                      "fp16_mixed_quantize": {
                                          "enabled": False}},
                "different_groups": {
                    "wq1": {"params": {"start_bits": 8, "target_bits": 8,
                                       "quantization_period": 0},
                            "modules": ["layers"]}}}}
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
        assert engine._infinity and engine._infinity_exec.compression is not None
        batch = _batch()
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
        assert losses[-1] < losses[0], losses
        ev = float(engine.eval_batch(batch))
        assert np.isfinite(ev)
        engine._infinity_exec.close()


class TestInfinityHostAdam:
    """use_cpu_adam inside the layer-streamed executor: the native fused
    C++ AdamW (csrc/adam/dstpu_cpu_adam.cpp) updates the store's chunks in
    place — the fp32 state never touches the device. Parity-checked against
    the on-device fused adam_chunk path (reference analogue: ZeRO-Offload's
    DeepSpeedCPUAdam vs FusedAdam parity, stage_1_and_2.py cpu_offload)."""

    def test_native_host_adam_parity(self, tmp_path):
        from deepspeed_tpu.ops.cpu_adam import cpu_adam_available
        if not cpu_adam_available():
            pytest.skip("native cpu_adam toolchain unavailable")
        cfg1 = _cfg_dict(tmp_path / "a", clip=1.0)
        cfg2 = _cfg_dict(tmp_path / "b", clip=1.0)
        cfg2["zero_optimization"]["offload_optimizer"]["use_cpu_adam"] = True
        e1, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg1)
        e2, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg2)
        assert e2._infinity_exec._host_adam == "native"
        assert e1._infinity_exec._host_adam is None
        # --- one step: masters bit-for-bit up to f32 rounding. (Multi-step
        # master comparison is chaotic by construction: a ~1e-7 f32 diff
        # flips bf16 param bits at rounding boundaries and Adam's early
        # bias correction (c2=1e-3) amplifies the resulting grad diffs.)
        o1, o2 = e1.train_batch(_batch()), e2.train_batch(_batch())
        assert math.isclose(float(o1["loss"]), float(o2["loss"]),
                            rel_tol=1e-5)
        assert math.isclose(float(o1["grad_norm"]), float(o2["grad_norm"]),
                            rel_tol=1e-4)
        for i in (0, e1._infinity_exec.cfg.num_layers - 1):
            m1 = np.asarray(e1._infinity_exec.store.read_opt(i))
            m2 = np.asarray(e2._infinity_exec.store.read_opt(i))
            np.testing.assert_allclose(m1, m2, atol=5e-7)
        # --- trajectory: losses track loosely and both decrease
        l1, l2 = [float(o1["loss"])], [float(o2["loss"])]
        for s in range(1, 5):
            b = _batch(seed=s)
            l1.append(float(e1.train_batch(b)["loss"]))
            l2.append(float(e2.train_batch(b)["loss"]))
        np.testing.assert_allclose(l1, l2, rtol=1e-3)
        e1._infinity_exec.close()
        e2._infinity_exec.close()

    def test_host_adam_checkpoint_roundtrip(self, tmp_path):
        from deepspeed_tpu.ops.cpu_adam import cpu_adam_available
        if not cpu_adam_available():
            pytest.skip("native cpu_adam toolchain unavailable")
        cfg = _cfg_dict(tmp_path / "a")
        cfg["zero_optimization"]["offload_optimizer"]["use_cpu_adam"] = True
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
        for s in range(2):
            engine.train_batch(_batch(seed=s))
        engine.save_checkpoint(str(tmp_path / "ck"))
        ref = float(engine.train_batch(_batch(seed=7))["loss"])
        cfg2 = _cfg_dict(tmp_path / "b")
        cfg2["zero_optimization"]["offload_optimizer"]["use_cpu_adam"] = True
        e2, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg2)
        e2.load_checkpoint(str(tmp_path / "ck"))
        got = float(e2.train_batch(_batch(seed=7))["loss"])
        assert math.isclose(ref, got, rel_tol=1e-5), (ref, got)
        engine._infinity_exec.close()
        e2._infinity_exec.close()


class TestInfinityMoQ:
    """MoQ composes with the layer-streamed executor (VERDICT r4 item 8):
    the per-layer jits fake-quant each streamed layer at its scheduled
    bit-width via the engine's traced ``_moq_bits`` side-channel."""

    def _cfg(self, tmp, start_bits=6):
        cfg = _cfg_dict(tmp)
        cfg["quantize_training"] = {
            "enabled": True,
            "quantize_bits": {"start_bits": start_bits, "target_bits": 4},
            "quantize_schedule": {"quantize_period": 2}}
        return cfg

    def test_streamed_moq_loss_parity(self, tmp_path):
        """Streamed forward at step 0 == monolithic forward over the SAME
        chunk-store weights with MoQ.apply at bits(0) — and the quantized
        loss measurably differs from the unquantized one."""
        engine, *_ = deepspeed_tpu.initialize(model=_model(),
                                              config=self._cfg(tmp_path))
        ex = engine._infinity_exec
        assert ex.moq
        params = _gather_stacked(ex)
        batch = _batch()
        from deepspeed_tpu.models.transformer import lm_loss
        moq = engine._moq
        ref_cfg = ex.cfg.__class__(**{**ex.cfg.__dict__, "scan_layers": True})
        ids = {"input_ids": jnp.asarray(batch["input_ids"])}
        qparams = moq.apply(params, jnp.asarray(moq.bits(0)))
        ref_loss = float(lm_loss(qparams, ids, ref_cfg, deterministic=True))
        noq_loss = float(lm_loss(params, ids, ref_cfg, deterministic=True))
        got = float(engine.train_batch(batch)["loss"])
        assert abs(got - ref_loss) < 3e-2, (got, ref_loss)
        # 6-bit fake-quant must actually bite (else the test proves nothing)
        assert abs(ref_loss - noq_loss) > 5 * abs(got - ref_loss) or \
            abs(ref_loss - noq_loss) > 1e-3, (ref_loss, noq_loss)
        ex.close()

    def test_streamed_moq_trains(self, tmp_path):
        engine, *_ = deepspeed_tpu.initialize(model=_model(),
                                              config=self._cfg(tmp_path))
        losses = [float(engine.train_batch(_batch(seed=s))["loss"])
                  for s in range(6)]
        assert np.isfinite(losses).all()
        # schedule advanced: bits dropped toward the target
        assert engine._moq.bits(engine.global_steps).max() < 6
        engine._infinity_exec.close()


class TestOffloadRouting:
    """Round 5: the layer-streamed executor is the ONE param-offload train
    path — the old non-streamed scan-fetch path (single-device-only, dead
    end per VERDICT r4 weakness #4) is deleted. Mixed cpu/nvme tiers
    collapse onto the nvme store with the host param cache on top."""

    def test_mixed_cpu_param_nvme_opt_routes_to_executor(self, tmp_path):
        cfg = _cfg_dict(tmp_path)
        cfg["zero_optimization"]["offload_param"] = {"device": "cpu"}
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
        assert engine._infinity and engine._infinity_exec is not None
        assert engine._infinity_backend == "nvme"
        m = engine.train_batch(_batch())
        assert np.isfinite(float(m["loss"]))
        engine._infinity_exec.close()

    def test_param_only_offload_routes_to_executor(self, tmp_path):
        cfg = _cfg_dict(tmp_path)
        cfg["zero_optimization"]["offload_optimizer"] = {"device": "none"}
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
        assert engine._infinity and engine._infinity_exec is not None
        m = engine.train_batch(_batch())
        assert np.isfinite(float(m["loss"]))
        engine._infinity_exec.close()
