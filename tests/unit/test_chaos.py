"""Chaos soak: N steps under a seeded fault schedule must end bit-identical
to the fault-free run, modulo replayed steps.

The schedule exercises every injection seam in one run: a transient device
fault (failed step -> probe cull -> rebuild from checkpoint), a corrupted
checkpoint payload (the rebuild's `latest` fails checksum validation and
walks back a tag), transient EIO on the checkpoint metadata path (absorbed
by retry_io), and a real-SIGTERM preemption (checkpoint-and-exit, then a
fresh agent resumes). Because checkpoints carry the engine rng chain and
batches are a pure function of the global step, every replayed step
recomputes exactly what the uninterrupted run computed — so the final
params AND optimizer state match bit-for-bit.

Slow tier: several engine (re)builds. Runs under tests/run_slow.sh with its
own per-module budget (CHAOS_BUDGET).
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.faults import FaultInjector, FaultSchedule
from deepspeed_tpu.robustness.preemption import Preempted, PreemptionHandler

pytestmark = pytest.mark.slow

N_STEPS = 50
SEQ, VOCAB = 32, 64
CKPT_INTERVAL = 5


@pytest.fixture(autouse=True)
def _clean_robustness_state():
    rb_faults.clear()
    rb_events.clear()
    yield
    rb_faults.clear()
    rb_events.clear()


def _factory():
    return make_model(TransformerConfig(
        vocab_size=VOCAB, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=SEQ, dtype=jnp.float32, attention_impl="xla"))


def _config(jsonl_path=None):
    cfg = {
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "elasticity": {"enabled": True, "max_train_batch_size": 64,
                       "micro_batch_sizes": [2, 4],
                       "min_gpus": 1, "max_gpus": 8},
        "steps_per_print": CKPT_INTERVAL,
    }
    if jsonl_path:
        cfg["telemetry"] = {"enabled": True, "jsonl_path": jsonl_path}
    return cfg


def _fetch(tree):
    return jax.tree.map(lambda a: np.asarray(jax.device_get(a)), tree)


def _run(agent_ctor, batches, n_steps):
    """Drive an agent to n_steps, restarting on Preempted (the 'new
    process after the launcher reaped us' path). failure_events are
    accumulated ACROSS restarts (each restart is a fresh agent)."""
    agent = agent_ctor()
    preemptions = failures = 0
    while agent.engine.global_steps < n_steps:
        # batch is a pure function of the step being attempted, so replays
        # after a rebuild consume exactly the original data
        try:
            agent.train_batch(
                lambda bs: batches[agent.engine.global_steps])
        except Preempted:
            preemptions += 1
            assert preemptions < 5, "preemption loop"
            failures += agent.failure_events
            agent = agent_ctor()
    return agent, preemptions, failures + agent.failure_events


class TestChaosSoak:
    def test_soak_bit_identical_to_fault_free(self, tmp_path, devices8):
        from deepspeed_tpu.elasticity import DSElasticAgent

        cfg = _config()
        rng = np.random.default_rng(99)
        # compute the elastic global batch once (same at every world size)
        probe_agent = DSElasticAgent(_factory, cfg, str(tmp_path / "probe"),
                                     checkpoint_interval=10**6)
        gb = probe_agent.batch_size
        probe_agent = None
        batches = [{"input_ids": rng.integers(0, VOCAB, (gb, SEQ),
                                              dtype=np.int32)}
                   for _ in range(N_STEPS + 4)]

        # ---- fault-free baseline -------------------------------------
        base_dir = str(tmp_path / "base")
        base, _, _ = _run(lambda: DSElasticAgent(
            _factory, _config(), base_dir,
            checkpoint_interval=CKPT_INTERVAL), batches, N_STEPS)
        assert base.engine.global_steps == N_STEPS
        base_params = _fetch(base.engine.state["params"])
        base_opt = _fetch(base.engine.state["opt"])
        base = None
        rb_events.clear()

        # ---- chaos run ------------------------------------------------
        # saves land at steps 5,10,15,... (post-install mutate indices
        # 0,1,2,...). The schedule:
        #   * ckpt_io EIO x2 at ops 0-1   -> retried, fault_recovered
        #   * corrupt_payload at save idx 1 (step 10's tag rots AFTER
        #     commit)
        #   * device_fault at step 12     -> failed step, cull to 4 for one
        #     probe (transient blip), rebuild; `latest`=step10 fails its
        #     checksum -> ckpt_fallback to step 5, replay 6..12
        #   * preempt at step 30          -> real SIGTERM, checkpoint-and-
        #     exit, fresh agent resumes at 30
        inj = rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "io_error", "op": "ckpt_io", "at": 0, "times": 2,
             "errno": "EIO"},
            {"kind": "corrupt_payload", "at": 1},
            {"kind": "device_fault", "step": 12, "survivors": 4,
             "probes": 1},
            {"kind": "preempt", "step": 30},
        ], seed=7)))
        chaos_dir = str(tmp_path / "chaos")
        jsonl = str(tmp_path / "tel" / "events.jsonl")
        handler = PreemptionHandler().install()

        def fresh_agent():
            # the restarted process starts with an un-latched handler
            handler.reset()
            return DSElasticAgent(
                _factory, _config(jsonl), chaos_dir,
                checkpoint_interval=CKPT_INTERVAL, preemption=handler)

        try:
            chaos, preemptions, failures = _run(fresh_agent, batches,
                                                N_STEPS)
        finally:
            handler.restore()
        assert chaos.engine.global_steps == N_STEPS

        # every scheduled fault actually fired
        fired_kinds = {f["kind"] for f in inj.fired}
        assert fired_kinds >= {"io_error", "corrupt_payload", "device_fault",
                               "preempt"}, fired_kinds
        assert preemptions == 1
        assert failures == 1                      # the device fault
        assert chaos.world == 8                   # transient blip: recovered

        # recovery evidence on the event stream
        recovered = rb_events.history("fault_recovered")
        assert any(e.get("kind") == "io" for e in recovered)      # retry_io
        assert any(e.get("kind") == "device" for e in recovered)  # rebuild
        fallbacks = rb_events.history("ckpt_fallback")
        assert fallbacks and fallbacks[0]["resolved"] == "global_step5"
        assert rb_events.history("preempted")

        # ... and drained into the telemetry JSONL sink
        tel_types = set()
        for p in glob.glob(os.path.join(os.path.dirname(jsonl), "*")):
            with open(p) as f:
                for line in f:
                    try:
                        tel_types.add(json.loads(line).get("type"))
                    except ValueError:
                        pass
        assert {"ckpt_fallback", "fault_recovered"} <= tel_types, tel_types

        # the final state is BIT-IDENTICAL to the fault-free run: replayed
        # steps recomputed the same math (checkpointed rng chain + step-
        # indexed batches), recoveries changed nothing
        chaos_params = _fetch(chaos.engine.state["params"])
        chaos_opt = _fetch(chaos.engine.state["opt"])
        for name, a, b in (("params", base_params, chaos_params),
                           ("opt", base_opt, chaos_opt)):
            flat_a = dict(jax.tree_util.tree_flatten_with_path(a)[0])
            flat_b = dict(jax.tree_util.tree_flatten_with_path(b)[0])
            assert flat_a.keys() == flat_b.keys()
            bad = [jax.tree_util.keystr(k) for k, va in flat_a.items()
                   if not np.array_equal(va, flat_b[k])]
            assert not bad, f"{name} leaves differ after chaos soak: {bad}"


class TestEngineLoadWalkback:
    def test_validated_but_unloadable_tag_walks_back(self, tmp_path,
                                                     devices8):
        """With checksums off, a size-preserving bit flip passes shallow
        validation but fails the Orbax restore — the ENGINE path must keep
        walking back to the previous good tag instead of bricking the
        elastic rebuild."""
        import deepspeed_tpu

        def build():
            engine, *_ = deepspeed_tpu.initialize(model=_factory(), config={
                "train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "bf16": {"enabled": False}, "steps_per_print": 10**6,
                "checkpoint": {"integrity_checksums": False}})
            return engine

        rng = np.random.default_rng(5)
        b = {"input_ids": rng.integers(0, VOCAB, (8, SEQ), dtype=np.int32)}
        engine = build()
        engine.train_batch(b)
        engine.save_checkpoint(str(tmp_path), tag="good")
        engine.train_batch(b)
        engine.save_checkpoint(str(tmp_path))  # latest = global_step2
        # size-preserving corruption of the newest tag's largest file
        tag2 = os.path.join(str(tmp_path), "global_step2")
        with open(os.path.join(tag2, "manifest.json")) as f:
            files = json.load(f)["files"]
        victim = os.path.join(
            tag2, max(files.items(), key=lambda kv: kv[1]["size"])[0])
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.write(os.urandom(size))          # same size, garbage bytes
        from deepspeed_tpu.robustness import integrity
        assert integrity.validate_tag(tag2, deep=False)[0]  # passes shallow

        e2 = build()
        e2.load_checkpoint(str(tmp_path))      # must walk back, not raise
        assert e2.global_steps == 1
        assert any(str(e.get("reason", "")).startswith("load-error")
                   for e in rb_events.history("ckpt_fallback"))


class TestEngineDataPositionResume:
    def test_client_state_carries_loader_position(self, tmp_path, devices8):
        """Engine-level satellite pin: save_checkpoint persists the attached
        loader's (epoch, pos, seed); load_checkpoint restores it, so the
        resumed run consumes exactly the batches the saved run had not."""
        import deepspeed_tpu
        from deepspeed_tpu.runtime.dataloader import DataLoader, RepeatingLoader

        data = [{"input_ids": np.full((SEQ,), i % VOCAB, np.int32)}
                for i in range(64)]

        def build():
            model = _factory()
            engine, *_ = deepspeed_tpu.initialize(model=model, config={
                "train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "bf16": {"enabled": False}, "steps_per_print": 10**6})
            loader = RepeatingLoader(DataLoader(
                data, batch_size=8, shuffle=True, seed=3))
            engine.attach_dataloader(loader)
            return engine, loader

        engine, loader = build()
        seen = []
        for _ in range(11):   # 8 batches/epoch: crosses into epoch 1
            b = next(loader)
            seen.append(b["input_ids"][:, 0].tolist())
            engine.train_batch(b)
        engine.save_checkpoint(str(tmp_path))
        ref = [next(loader)["input_ids"][:, 0].tolist() for _ in range(6)]

        engine2, loader2 = build()
        engine2.load_checkpoint(str(tmp_path))
        assert engine2.global_steps == 11
        assert loader2.state_dict() == {"epoch": 1, "pos": 3, "seed": 3}
        resumed = [next(loader2)["input_ids"][:, 0].tolist()
                   for _ in range(6)]
        assert resumed == ref    # no replay, no skip
        # and the restored rng chain matches the saved engine's
        assert np.array_equal(engine._rng_key_data(),
                              engine2._rng_key_data())
