"""graft-lint static analysis (deepspeed_tpu/analysis) — grown from
test_spmd_clean.py per the analysis-subsystem issue.

Reference counterpart: DeepSpeed has no compiler to interrogate — its
canonical silent failure is an extra allreduce nobody notices until the
bill. Here each analyzer is exercised on a clean config AND a seeded
violation, and the collective census for ZeRO stage 2 vs stage 3 is pinned
to exact counts on a 2-device mesh: a silently added/removed collective is
a hard test failure. This module is the CI gate for the lint subsystem
(the CLI exit-code tests at the bottom are what a pipeline would run).
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis import (AnalysisSettings, Finding, Report,
                                    capture_spmd_warnings, collective_census,
                                    estimate_peak_hbm,
                                    jaxpr_primitive_census, lower_program,
                                    parse_collectives, parse_donated_params,
                                    parse_entry_params, parse_remat_census,
                                    parse_spmd_remat_warning,
                                    parse_upcasts, replicated_tensor_bytes,
                                    shape_bytes)
from deepspeed_tpu.models import TransformerConfig, make_model

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def tiny_model(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla")
    base.update(kw)
    return make_model(TransformerConfig(**base), name="lint-tiny")


def stage_config(stage, axes, **overrides):
    cfg = {"train_batch_size": 4,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "bf16": {"enabled": False},
           "zero_optimization": {"stage": stage,
                                 "stage3_param_persistence_threshold": 0},
           "mesh": {"axes": axes},
           "steps_per_print": 100}
    cfg.update(overrides)
    return cfg


BATCH = {"input_ids": np.zeros((4, 16), np.int32)}


def audit_stage(stage, axes, model=None, devices=None, **overrides):
    engine, *_ = deepspeed_tpu.initialize(
        model=model or tiny_model(),
        config=stage_config(stage, axes, **overrides),
        devices=devices or jax.devices()[:2])
    return engine.audit(batch=BATCH)


# plain-config audits are deterministic per (stage, axes): cache them so the
# clean-config gate and the memory-law pins share one lowering per stage
# instead of re-compiling the engine per test (quick-tier wall budget)
_AUDIT_CACHE = {}


def cached_audit(stage, axes, devices):
    key = (stage, tuple(sorted(axes.items())))
    if key not in _AUDIT_CACHE:
        _AUDIT_CACHE[key] = audit_stage(stage, axes, devices=devices)
    return _AUDIT_CACHE[key]


# --------------------------------------------------------------------------
# parsers (pure text, no compilation)
# --------------------------------------------------------------------------

class TestHloParsers:
    def test_shape_bytes(self):
        assert shape_bytes("f32", "2,32,32") == 8192
        assert shape_bytes("bf16", "1024") == 2048
        assert shape_bytes("pred", "") == 1  # scalar

    def test_parse_collectives_with_decoys(self):
        hlo = "\n".join([
            # real ops: plain, async pair (tuple wraps operand+result: the
            # op size is the LARGEST element, not the double-counting sum),
            # variadic tuple result
            "  %all-reduce.1 = f32[16]{0} all-reduce(f32[16]{0} %x), "
            "channel_id=1, to_apply=%add",
            "  %ag = (f32[2,32]{1,0}, f32[2,64]{1,0}) "
            "all-gather-start(f32[2,32]{1,0} %y), channel_id=2",
            "  %agd = f32[2,64]{1,0} all-gather-done(%ag)",
            "  %rs = (f32[8]{0}, f32[8]{0}) reduce-scatter(%a, %b), "
            "channel_id=3",
            # decoys: operand reference, metadata op_name (underscored)
            "  %copy.1 = f32[2,64]{1,0} copy(f32[2,64]{1,0} %all-gather.9)",
            '  %fusion.2 = f32[4]{0} fusion(%z), metadata={op_name='
            '"jit(f)/all_gather"}',
        ])
        ops = parse_collectives(hlo)
        kinds = sorted(op.kind for op in ops)
        assert kinds == ["all-gather", "all-reduce", "reduce-scatter"]
        by_kind = {op.kind: op for op in ops}
        assert by_kind["all-reduce"].nbytes == 64
        assert by_kind["all-gather"].nbytes == 512   # max, not 256+512
        assert by_kind["all-gather"].is_async
        assert by_kind["reduce-scatter"].nbytes == 64  # variadic summed

    def test_census_min_bytes(self):
        ops = parse_collectives(
            "  %r = f32[4]{0} all-reduce(%x), channel_id=1\n"
            "  %big = f32[1024,1024]{1,0} all-reduce(%y), channel_id=2\n")
        assert collective_census(ops)["all-reduce"]["count"] == 2
        big = collective_census(ops, min_bytes=1 << 20)
        assert big["all-reduce"] == {"count": 1, "bytes": 4 << 20}

    def test_stablehlo_alias_attribution_per_arg(self):
        """tf.aliasing_output must be charged to ITS argument, not an
        earlier undecorated one (attr dicts contain commas/quoted braces)."""
        from deepspeed_tpu.analysis import hlo_parse
        st = ('func.func public @main(%arg0: tensor<256x256xf32>, '
              '%arg1: tensor<256x256xf32> {mhlo.sharding = '
              '"{devices=[2]<=[2]}", tf.aliasing_output = 0 : i32}) '
              '-> (tensor<256x256xf32>) {')
        assert hlo_parse.parse_aliased_args_stablehlo(st) == [1]

    def test_parse_donated_params(self):
        hlo = ("HloModule jit_f, input_output_alias={ {0}: (0, {}, "
               "may-alias), {1}: (3, {}, must-alias) }, "
               "entry_computation_layout={...}\n  body\n")
        assert parse_donated_params(hlo) == [0, 3]
        assert parse_donated_params("HloModule jit_g\n  body\n") == []

    def test_parse_upcasts(self):
        hlo = "\n".join([
            "  %c1 = f32[512,512]{1,0} convert(bf16[512,512]{1,0} %x)",
            "  %c2 = f32[4]{0} convert(bf16[4]{0} %y)",       # tiny
            "  %c3 = bf16[512,512]{1,0} convert(f32[512,512]{1,0} %z)",  # down
        ])
        ups = parse_upcasts(hlo, min_bytes=1 << 20)
        assert len(ups) == 1 and ups[0].nbytes == 1 << 20
        assert ups[0].from_dtype == "bf16"

    def test_replicated_tensor_scanner(self):
        """replicated_tensor_bytes flags large replicated float tensors and
        ignores small/sharded ones (kept from test_spmd_clean)."""
        hlo = "\n".join([
            "  %big = f32[1024,1024] broadcast(%x), sharding={replicated}",
            "  %small = f32[4,4] broadcast(%x), sharding={replicated}",
            "  %sharded = f32[1024,1024] add(%a, %b), "
            "sharding={devices=[4,1]<=[4]}",
            "  %bigbf = bf16[2048,1024]{1,0} copy(%c), sharding={replicated}",
        ])
        hits = replicated_tensor_bytes(hlo, min_bytes=1 << 20)
        assert len(hits) == 2
        assert {h[0] for h in hits} == {1024 * 1024 * 4, 2048 * 1024 * 2}
        # only the RESULT shape is charged: a tiny replicated result with a
        # big float operand must not be billed for the operand
        decoy = ("  %p = pred[4]{0} compare(f32[1024,1024]{1,0} %a, %b), "
                 "sharding={replicated}")
        assert replicated_tensor_bytes(decoy, min_bytes=1 << 20) == []

    def test_replicated_scanner_stablehlo(self):
        st = ('    %0 = stablehlo.custom_call @Sharding(%arg0) '
              '{mhlo.sharding = "{replicated}"} : (tensor<512x512xf32>) '
              '-> tensor<512x512xf32>')
        hits = replicated_tensor_bytes(st, min_bytes=1 << 20)
        assert hits == [(512 * 512 * 4, st.strip()[:200])]

    def test_capture_helper_sees_fd2_writes(self):
        # must capture C-level fd-2 writes, not just sys.stderr
        # (kept from test_spmd_clean)
        matches = []
        with capture_spmd_warnings(matches):
            os.write(2, b"[SPMD] Involuntary full rematerialization line\n")
        assert len(matches) == 1


# a real spmd_partitioner.cc line (captured from the 8-dev fsdp=4xtensor=2
# dryrun — the pre-existing involuntary-remat failure this audit diagnoses)
_SPMD_WARN_LINE = (
    "2026-08-03 10:11:21.614278: E external/xla/xla/service/spmd/"
    "spmd_partitioner.cc:613] [spmd] Involuntary full rematerialization. "
    "The compiler was not able to go from sharding {devices=[1,8]<=[8]} to "
    "{devices=[2,1,4]<=[4,2]T(1,0) last_tile_dim_replicate} without doing a "
    "full rematerialization of the tensor for HLO operation: %transpose.11 "
    "= f32[128,64]{0,1} transpose(f32[64,128]{1,0} %get-tuple-element), "
    "dimensions={1,0}, sharding={devices=[1,8]<=[8]}, metadata={op_name="
    '"jit(train_step)/jit(main)/while/body/transpose" source_file='
    '"/root/repo/deepspeed_tpu/models/transformer.py" source_line=1215}. '
    "You probably want to enrich the sharding annotations to prevent this "
    "from happening.")


class TestMemoryParsers:
    """Pure-text liveness/remat parsers — no compilation."""

    # 4 MiB param (donated), 32 KiB batch arg, one 4 MiB temp; the updated
    # output writes into the donated param's buffer
    _HLO = "\n".join([
        "HloModule jit_step, is_scheduled=true, input_output_alias="
        "{ {0}: (0, {}, may-alias) }",
        "",
        "ENTRY %main (p0: f32[1024,1024], p1: f32[8,1024]) -> "
        "(f32[1024,1024]) {",
        "  %p0 = f32[1024,1024]{1,0} parameter(0)",
        "  %p1 = f32[8,1024]{1,0} parameter(1)",
        "  %big = f32[1024,1024]{1,0} multiply(%p0, %p0)",
        "  %t = f32[8,1024]{1,0} dot(%p1, %big)",
        "  %upd = f32[1024,1024]{1,0} add(%big, %p0)",
        "  ROOT %out = (f32[1024,1024]{1,0}) tuple(%upd)",
        "}",
    ])

    def test_entry_params_per_device_shapes(self):
        ps = parse_entry_params(self._HLO)
        assert [(p.number, p.nbytes) for p in ps] == [(0, 1 << 22),
                                                      (1, 32768)]
        assert ps[0].dtype == "f32" and ps[0].dims == "1024,1024"

    def test_peak_honors_donation_alias(self):
        est = estimate_peak_hbm(self._HLO,
                                param_classes={0: "params",
                                               1: "activations"})
        # peak at the %t dot: p0 + p1 + %big + %t; %upd reuses p0's buffer
        # (input_output_alias) so the update adds nothing
        assert est.peak_bytes == 2 * (1 << 22) + 2 * 32768
        assert est.param_bytes == {"params": 1 << 22,
                                   "activations": 32768}
        # a missed donation is double memory: same module without the
        # header alias map holds %upd as a second 4 MiB allocation
        # alongside p0 and %big
        undonated = self._HLO.replace(
            ", input_output_alias={ {0}: (0, {}, may-alias) }", "")
        est2 = estimate_peak_hbm(undonated)
        assert est2.peak_bytes == 3 * (1 << 22) + 32768

    def test_gte_selects_one_tuple_element(self):
        """Element-level aliasing: a gte of one small tuple element must
        not keep the big sibling alive (else every fused K-step carry
        would model as Kx memory)."""
        hlo = "\n".join([
            "HloModule jit_g, is_scheduled=true",
            "",
            "ENTRY %main (p0: f32[1024,1024], p1: f32[4]) -> f32[4] {",
            "  %p0 = f32[1024,1024]{1,0} parameter(0)",
            "  %p1 = f32[4]{0} parameter(1)",
            "  %a = f32[1024,1024]{1,0} exponential(%p0)",
            "  %b = f32[4]{0} ceil(%p1)",
            "  %tup = (f32[1024,1024]{1,0}, f32[4]{0}) tuple(%a, %b)",
            "  %sel = f32[4]{0} get-tuple-element(%tup), index=1",
            "  %c = f32[1024,1024]{1,0} cosine(%p0)",
            "  %d = f32[4]{0} reduce(%c, %p1), to_apply=%add",
            "  ROOT %use = f32[4]{0} add(%sel, %d)",
            "}",
        ])
        est = estimate_peak_hbm(hlo)
        # %a dies at %tup (only %b flows on through %sel): peak holds ONE
        # 4 MiB temp at a time, params + max(a, c) + scalars
        assert est.peak_bytes < (1 << 22) + (1 << 22) + (1 << 22)
        assert est.peak_bytes >= (1 << 22) + (1 << 22)

    def test_remat_census_markers(self):
        hlo = "\n".join([
            '  %f = f32[4]{0} fusion(%x), metadata={op_name="jit(s)/'
            'transpose(jvp(checkpoint))/rematted_computation/dot_general"}',
            '  %g = f32[4]{0} fusion(%y), metadata={op_name="jit(s)/'
            'transpose(jvp(checkpoint))/mul"}',
            '  %h = f32[4]{0} fusion(%z), metadata={op_name="jit(s)/tanh"}',
        ])
        census = parse_remat_census(hlo)
        assert census == {"remat_ops": 1, "bwd_ops": 2, "total_ops": 3}

    def test_spmd_warning_structured(self):
        w = parse_spmd_remat_warning(_SPMD_WARN_LINE)
        assert w["op"] == "%transpose.11"
        assert w["shape"] == "f32[128,64]" and w["nbytes"] == 32768
        assert w["from_sharding"] == "{devices=[1,8]<=[8]}"
        assert w["source_file"].endswith("models/transformer.py")
        assert w["source_line"] == 1215
        assert "while/body/transpose" in w["op_name"]

    def test_remat_audit_findings_from_artifacts(self):
        """RematAudit is a pure structure pass: involuntary remat comes
        from the compile-time capture in meta, the inert-policy warning
        from the metadata census — no lowering needed to test either."""
        from deepspeed_tpu.analysis import ProgramArtifacts, RematAudit
        art = ProgramArtifacts(
            name="p", optimized_hlo="",
            meta={"spmd_warnings": [parse_spmd_remat_warning(
                _SPMD_WARN_LINE)]})
        fs = RematAudit().analyze(art, AnalysisSettings())
        assert [f.rule for f in fs] == ["involuntary-remat"]
        assert fs[0].severity == "error" and fs[0].nbytes == 32768
        assert fs[0].data["source_line"] == 1215
        # configured policy, backward present, nothing rematerialized
        hlo = ("HloModule m, is_scheduled=true\n\n"
               "ENTRY %e (a: f32[4]) -> f32[4] {\n"
               "  %a = f32[4]{0} parameter(0)\n"
               "  ROOT %x = f32[4]{0} negate(%a), metadata={op_name="
               '"jit(s)/transpose(jvp(f))/neg"}\n}\n')
        art2 = ProgramArtifacts(name="p", optimized_hlo=hlo,
                                meta={"remat_policy": "dots_saveable"})
        fs2 = RematAudit().analyze(art2, AnalysisSettings())
        assert [f.rule for f in fs2] == ["remat-policy-inert"]
        assert fs2[0].severity == "warning"


# --------------------------------------------------------------------------
# seeded-violation corpus: every analyzer must flag its planted defect
# --------------------------------------------------------------------------

_CORPUS_RULES = {
    "undonated-state": "donation-missing",
    "extra-collective": "collective-census-drift",
    "f32-upcast": "dtype-upcast",
    "replicated-budget": "replication-over-budget",
    "census-drift": "collective-census-drift",
    "fused-hoist": "collective-census-drift",
    "telemetry-leak": "donation-missing",
    "deferred-sync-regression": "collective-census-drift",
    "remat-missing": "memory-peak",
    "stage3-replicated-opt": "memory-law",
    "paged-cache-leak": "memory-peak",
    "tp-serving-replicated-pool": "replication-over-budget",
    "quantized-weight-replicated": "replication-over-budget",
    "adapter-slot-leak": "pool-growth",
    "handoff-recompute": "ttft-growth",
    "serving-blind-stall": "serving-phase-stall",
    "tracing-sync-leak": "tracing-sync-leak",
    "staging-buffer-alias": "buffer-alias",
    "allocator-unlocked-share": "refcount-race",
    "drain-schema-skew": "reader-writer-skew",
    "fenceless-failover": "double-serve",
}


class TestSeededCorpus:
    @pytest.mark.parametrize("name", sorted(_CORPUS_RULES))
    def test_corpus_entry_flagged(self, name, devices8):
        from deepspeed_tpu.analysis.corpus import run_corpus
        report = run_corpus(name, devices=devices8[:2])
        assert not report.ok, f"{name}: seeded violation not flagged"
        rules = {f.rule for f in report.findings}
        assert _CORPUS_RULES[name] in rules, (name, rules)

    def test_deferred_sync_regression_reports_exposed(self, devices8):
        """The gas=4 per-microbatch reduce-scatter corpus entry must be
        flagged BOTH ways: census drift (gas x inflation vs the deferred
        1-per-step pin) AND exposed collectives from the overlap audit."""
        from deepspeed_tpu.analysis.corpus import run_corpus
        report = run_corpus("deferred-sync-regression", devices=devices8[:2])
        rules = {f.rule for f in report.findings}
        assert "collective-census-drift" in rules
        assert "collective-exposed" in rules
        ov = report.overlap["deferred_step"]
        assert ov["exposed"]["count"] == 4 and ov["overlapped"]["count"] == 0

    def test_stage3_replicated_opt_fires_both_rules(self, devices8):
        """The replicated-moments defect must be caught from BOTH ends:
        the ZeRO memory law (per-device opt bytes = logical instead of
        logical/dp) and the replication budget (explicit replicated
        shardings over the floor)."""
        from deepspeed_tpu.analysis.corpus import run_corpus
        report = run_corpus("stage3-replicated-opt", devices=devices8[:2])
        rules = {f.rule for f in report.findings}
        assert {"memory-law", "replication-over-budget"} <= rules, rules
        sb = report.memory["stage3_step"]["state_bytes"]
        assert sb["opt"]["per_device"] == sb["opt"]["logical"]   # defect
        assert sb["params"]["per_device"] == sb["params"]["logical"] // 2

    def test_remat_fix_stays_under_the_corpus_budget(self, devices8):
        """The remat-missing entry's defect is the MISSING checkpoint: the
        same long-scan program with the body checkpointed must clear the
        identical 18 MiB budget, with recomputation visible in the remat
        census."""
        from deepspeed_tpu.analysis.corpus import (_FakePlan,
                                                   _long_scan_program,
                                                   _stage0_config)
        from deepspeed_tpu.analysis.lint import analyze_programs
        art = _long_scan_program(remat=True, devices=devices8[:2])
        report = analyze_programs(
            [art], _stage0_config(), _FakePlan(),
            settings=AnalysisSettings(max_hbm_bytes=18 << 20))
        assert report.ok, report.summary()
        mem = report.memory["long_scan_step"]
        assert mem["peak_hbm_bytes"] <= 18 << 20
        assert mem["remat"]["remat_ops"] > 0   # recomputation happened

    def test_suppression_accepts_known_finding(self, devices8):
        from deepspeed_tpu.analysis.corpus import run_corpus
        report = run_corpus("f32-upcast", devices=devices8[:2])
        report.suppress(["dtype-upcast"])
        assert report.ok and report.suppressed

    def test_baseline_roundtrip(self):
        rep = Report(findings=[Finding(rule="dtype-upcast", program="p",
                                       ident="f32[512,512]", message="x")],
                     census={"p": {"all-reduce": {"count": 2, "bytes": 64}}})
        base = rep.baseline_dict()
        rep2 = Report(findings=[Finding(rule="dtype-upcast", program="p",
                                        ident="f32[512,512]", message="x")])
        rep2.apply_baseline(base)
        assert rep2.ok and len(rep2.suppressed) == 1

    def test_baseline_never_suppresses_census_drift(self):
        """Accepting a drifted state must re-pin the census, not suppress
        drift-by-key — a FUTURE extra collective of the same kind has the
        same key and would sail through the gate it exists for."""
        from deepspeed_tpu.analysis import compare_census
        census = {"all-reduce": {"count": 3, "bytes": 96}}
        drift = compare_census(census, {"all-reduce": 2}, "p", source="pin")
        rep = Report(findings=list(drift), census={"p": census})
        base = rep.baseline_dict()
        assert base["findings"] == []           # drift keys not recorded
        assert base["census"]["p"]["all-reduce"]["count"] == 3  # re-pinned
        # a later run with one MORE all-reduce still fails against the
        # accepted baseline
        worse = {"all-reduce": {"count": 4, "bytes": 128}}
        rep2 = Report(findings=compare_census(worse, base["census"]["p"],
                                              "p", source="baseline"))
        rep2.apply_baseline(base)
        assert not rep2.ok


# --------------------------------------------------------------------------
# clean configs: ZeRO stages 0-3 lint clean; stage 2 vs 3 census is PINNED
# --------------------------------------------------------------------------

# exact collective censuses for the tiny model / 4x16 batch / 2-device mesh,
# adamw, f32 (measured; stable across xla_backend_optimization_level).
# If a deliberate program change shifts these, re-measure with:
#   python -m deepspeed_tpu.analysis.lint --config <cfg> --write-baseline
# An UNEXPLAINED shift is the bug this test exists to catch.
STAGE2_CENSUS = {"all-reduce": 41, "all-gather": 22, "all-to-all": 2}
# re-pinned for ISSUE 8's tied-embedding head: contracting the untransposed
# table (lm_head_logits dot_general) needs one FEWER all-gather than
# materializing tok_embed.T under the stage-3 vocab sharding (was 46)
STAGE3_CENSUS = {"all-gather": 45, "all-reduce": 30, "all-to-all": 17}


class TestCleanConfigs:
    @pytest.mark.parametrize("stage,axes", [
        (0, {"data": 2}), (1, {"data": 2}),
        (2, {"data": 2}), (3, {"fsdp": 2})])
    def test_zero_stage_lints_clean(self, stage, axes, devices8):
        report = cached_audit(stage, axes, devices8[:2])
        assert report.ok and not report.findings, report.summary()
        assert report.census["train_step"], "no collectives parsed"

    def test_stage2_vs_stage3_census_pinned(self, devices8):
        """The collective-audit acceptance gate: exact counts per stage on a
        2-device mesh; an extra (or vanished) collective is a hard failure."""
        for stage, axes, want in ((2, {"data": 2}, STAGE2_CENSUS),
                                  (3, {"fsdp": 2}, STAGE3_CENSUS)):
            report = audit_stage(stage, axes, devices=devices8[:2],
                                 analysis={"expect_collectives": want})
            assert report.ok, f"stage {stage}:\n{report.summary()}"
            got = {k: c["count"]
                   for k, c in report.census["train_step"].items()}
            assert got == want, f"stage {stage} census drifted: {got}"

    @pytest.mark.slow
    def test_fused_program_census_scales_by_k(self, devices8):
        """pipeline.fuse_steps=K lowers a second artifact (train_step_fused)
        whose census must be EXACTLY Kx the single-step pins: a collective
        hoisted out of (or duplicated into) the unrolled loop is drift.
        Its MEMORY must not scale with K: the inter-step state stays at
        boundary shardings in the loop carry, so the modeled peak HBM of
        the K-fused program pins ~1x the single step's, not Kx."""
        report = audit_stage(2, {"data": 2}, devices=devices8[:2],
                             pipeline={"fuse_steps": 2},
                             analysis={"expect_collectives": STAGE2_CENSUS})
        assert report.ok, report.summary()
        single = {k: c["count"] for k, c in report.census["train_step"].items()}
        fused = {k: c["count"]
                 for k, c in report.census["train_step_fused"].items()}
        assert single == STAGE2_CENSUS
        assert fused == {k: 2 * v for k, v in STAGE2_CENSUS.items()}, fused
        peak1 = report.memory["train_step"]["peak_hbm_bytes"]
        peakk = report.memory["train_step_fused"]["peak_hbm_bytes"]
        assert peak1 > 0
        # K=2: Kx would be >= 2.0; the carried state models ~1.3x (XLA's
        # own buffer assignment says 1.16x for this program pair)
        assert peakk < 1.6 * peak1, (peak1, peakk)

    def test_extra_allreduce_in_model_fails_pin(self, devices8):
        """A model-level silently-added cross-replica reduction must break
        the stage-2 pin — the reference's unnoticeable extra allreduce is a
        hard failure here."""
        from deepspeed_tpu.analysis.corpus import NoisyLossModel
        report = audit_stage(
            2, {"data": 2}, model=NoisyLossModel(tiny_model()),
            devices=devices8[:2],
            analysis={"expect_collectives": STAGE2_CENSUS})
        assert not report.ok
        drift = [f for f in report.findings
                 if f.rule == "collective-census-drift"
                 and f.data["got"] > f.data["expected"]]
        assert drift, report.summary()

    def test_donation_covers_whole_state(self, devices8):
        """Every param/optimizer buffer of the stage-2 step aliases an
        output (missed donation = double memory)."""
        from deepspeed_tpu.analysis import lower_engine_programs
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_model(), config=stage_config(2, {"data": 2}),
            devices=devices8[:2])
        art = lower_engine_programs(engine, batch=BATCH)[0]
        donated = parse_donated_params(art.optimized_hlo)
        assert len(donated) == len(art.donatable_paths)
        assert donated == list(range(len(art.donatable_paths)))


# --------------------------------------------------------------------------
# memory lint: ZeRO memory law + peak breakdown on the 2-dev mesh
# --------------------------------------------------------------------------

class TestMemoryLintEngine:
    def test_memory_law_stage0_vs_stage3_pinned(self, devices8):
        """The acceptance pin: per-device opt-state bytes verify the ZeRO
        memory law on the 2-dev mesh — stage 0 holds the FULL optimizer
        state on every device (per-device == logical, exactly), stage 3
        shards it ~1/dp (slack only from unshardable small leaves), and
        the stage-3/stage-0 per-device ratio is ~1/dp. Same law for
        stage-3 params. The numbers come from the compiled modules' entry
        parameter shapes — post-SPMD fact, not configuration intent."""
        rep0 = cached_audit(0, {"data": 2}, devices8[:2])
        rep3 = cached_audit(3, {"fsdp": 2}, devices8[:2])
        s0 = rep0.memory["train_step"]["state_bytes"]
        s3 = rep3.memory["train_step"]["state_bytes"]
        # identical logical state across stages (same model/optimizer)
        assert s3["opt"]["logical"] == s0["opt"]["logical"]
        assert s3["params"]["logical"] == s0["params"]["logical"]
        # stage 0: everything replicated — exact equality
        assert s0["opt"]["per_device"] == s0["opt"]["logical"]
        assert s0["params"]["per_device"] == s0["params"]["logical"]
        # stage 3: ~1/dp with dp=2; <=5% slack for unshardable leaves
        for cls in ("opt", "params"):
            half = s3[cls]["logical"] / 2
            assert half <= s3[cls]["per_device"] <= 1.05 * half, \
                (cls, s3[cls])
        ratio = s3["opt"]["per_device"] / s0["opt"]["per_device"]
        assert abs(ratio - 0.5) < 0.02, ratio

    def test_audit_reports_peak_with_class_breakdown(self, devices8):
        """engine.audit() must report per-program peak_hbm_bytes with the
        params/grads/opt/activations breakdown (the acceptance surface
        bench.py and the CLI JSON expose)."""
        report = cached_audit(2, {"data": 2}, devices8[:2])
        mem = report.memory["train_step"]
        assert mem["peak_hbm_bytes"] > 0
        bd = mem["peak_breakdown"]
        assert {"params", "grads", "opt", "activations"} <= set(bd)
        # the donated state is resident at peak: params are exact
        assert bd["params"] == mem["state_bytes"]["params"]["per_device"]
        assert sum(bd.values()) == mem["peak_hbm_bytes"]
        # fwd/bwd boundary liveness + remat census ride the same measure
        assert mem["boundary_activation_bytes"] > 0   # no remat configured
        assert mem["remat"]["remat_ops"] == 0
        assert mem["remat"]["bwd_ops"] > 0

    @pytest.mark.slow
    def test_memory_lint_changes_no_numerics(self, devices8):
        """Bit-for-bit: auditing with the memory gate armed is a pure
        read of the compiled artifact — training with audit() calls and
        analysis.max_hbm_bytes set produces byte-identical params to
        training without. Slow tier: numerical-parity suites run with
        production codegen (two engine builds + 6 steps, ~9s; re-tiered
        with the PR-6 quick additions to hold the 180s tier budget)."""
        def run(with_lint):
            overrides = ({"analysis": {"max_hbm_bytes": 1 << 40}}
                         if with_lint else {})
            engine, *_ = deepspeed_tpu.initialize(
                model=tiny_model(),
                config=stage_config(2, {"data": 2}, **overrides),
                devices=devices8[:2])
            rng = np.random.default_rng(7)
            for i in range(3):
                batch = {"input_ids": rng.integers(
                    0, 64, size=(4, 16), dtype=np.int32)}
                engine.train_batch(batch)
                if with_lint and i == 1:
                    report = engine.audit(batch=BATCH)
                    assert report.ok, report.summary()
            return jax.device_get(engine.state["params"])
        base = run(False)
        linted = run(True)
        flat_b = jax.tree_util.tree_leaves(base)
        flat_l = jax.tree_util.tree_leaves(linted)
        assert len(flat_b) == len(flat_l)
        for a, b in zip(flat_b, flat_l):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------------------------
# dtype/flash satellites
# --------------------------------------------------------------------------

class TestDtypeAndFlash:
    def test_bf16_clean_config_no_upcast_findings(self, devices8):
        report = audit_stage(2, {"data": 2},
                             model=tiny_model(dtype=jnp.bfloat16),
                             devices=devices8[:2])
        assert not [f for f in report.findings if f.rule == "dtype-upcast"], \
            report.summary()

    def test_flash_survives_static_windows_unrolled(self):
        """attn_windows=(0, w): the unrolled path passes STATIC windows, so
        the global layer keeps the flash/Pallas kernel; under scan the
        traced window pushes every layer to the XLA path (documented cost).
        Confirmed at jaxpr level via the analysis census."""
        counts = {}
        for scan in (False, True):
            cfg = TransformerConfig(
                vocab_size=64, hidden_size=128, num_layers=2, num_heads=2,
                max_seq_len=128, dtype=jnp.float32, attention_impl="pallas",
                attn_windows=(0, 8), scan_layers=scan)
            model = make_model(cfg, name="win")
            params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
            batch = {"input_ids": jax.ShapeDtypeStruct((2, 128), jnp.int32)}
            census = jaxpr_primitive_census(
                lambda p, b: model.loss_fn(p, b, None, True), params, batch)
            counts[scan] = census.get("pallas_call", 0)
        assert counts[False] == 1, counts  # global layer keeps flash
        assert counts[True] == 0, counts   # scan: traced window, XLA path


# --------------------------------------------------------------------------
# CLI — the CI gate a pipeline runs
# --------------------------------------------------------------------------

def _run_cli(*args, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["DSTPU_LOG_LEVEL"] = "error"
    # replace any inherited XLA_FLAGS with just the compile-speed flag:
    # the CLI appends its own virtual-device count, and census pins are
    # stable across optimization levels (see STAGE2_CENSUS note) while
    # full-opt compile costs ~2x the wall of the whole test
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.analysis.lint", *args],
        capture_output=True, text=True, timeout=timeout, env=env,
        cwd=REPO_ROOT)


class TestLintCLI:
    def test_clean_config_exits_zero_with_census(self, tmp_path):
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps(stage_config(2, {"data": 2})))
        out = tmp_path / "report.json"
        proc = _run_cli("--config", str(cfg), "--json", str(out))
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(out.read_text())
        assert report["ok"] and not report["findings"]
        census = report["census"]["train_step"]
        for kind, c in census.items():
            assert c["count"] > 0 and c["bytes"] > 0
        assert "all-reduce" in census
        # the memory-lint surface rides the same JSON report
        mem = report["memory"]["train_step"]
        assert mem["peak_hbm_bytes"] > 0
        assert {"params", "grads", "opt", "activations"} \
            <= set(mem["peak_breakdown"])
        assert mem["state_bytes"]["opt"]["per_device"] > 0

    def test_seeded_violation_exits_nonzero(self, tmp_path):
        proc = _run_cli("--corpus", "f32-upcast")
        assert proc.returncode == 1, proc.stderr[-2000:]
        assert "dtype-upcast" in proc.stderr

    @pytest.mark.slow
    def test_baseline_gate(self, tmp_path):
        """--write-baseline then --baseline passes; a different config
        against the same baseline fails with census drift."""
        cfg2 = tmp_path / "s2.json"
        cfg2.write_text(json.dumps(stage_config(2, {"data": 2})))
        base = tmp_path / "base.json"
        assert _run_cli("--config", str(cfg2), "--write-baseline",
                        str(base)).returncode == 0
        assert _run_cli("--config", str(cfg2), "--baseline",
                        str(base)).returncode == 0
        cfg3 = tmp_path / "s3.json"
        cfg3.write_text(json.dumps(stage_config(3, {"fsdp": 2})))
        proc = _run_cli("--config", str(cfg3), "--baseline", str(base))
        assert proc.returncode == 1
        assert "collective-census-drift" in proc.stderr
