"""Diffusion UNet family (reference: model_implementations/diffusers/
{unet,vae}.py + module_inject containers for UNet/CLIP/VAE + csrc/spatial).
The TPU equivalents of the reference's wrappers are jit caching and XLA
conv-bias fusion; what these tests pin down is the real surface: a spatial
ModelSpec trains under the engine (ZeRO stages) and runs under
init_inference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import UNetConfig, make_unet_model, unet_forward

# quick tier: `pytest -m 'not slow'` skips this module (conv mesh compiles)
pytestmark = pytest.mark.slow


def _cfg():
    return UNetConfig(in_channels=3, out_channels=3, base_channels=16,
                      channel_mults=(1, 2), num_res_blocks=1,
                      time_embed_dim=32, attn_heads=2, norm_groups=4,
                      dtype=jnp.float32)


def _batch(B=4, H=16, seed=0):
    rng = np.random.default_rng(seed)
    return {"x": rng.normal(size=(B, H, H, 3)).astype(np.float32),
            "t": rng.integers(0, 1000, (B,)).astype(np.int32),
            "target": rng.normal(size=(B, H, H, 3)).astype(np.float32)}


def test_forward_shapes_and_grads():
    cfg = _cfg()
    model = make_unet_model(cfg)
    p = model.init(jax.random.PRNGKey(0))
    b = _batch()
    out = unet_forward(p, jnp.asarray(b["x"]), jnp.asarray(b["t"]), cfg)
    assert out.shape == (4, 16, 16, 3)
    loss, grads = jax.value_and_grad(model.loss_fn)(p, b)
    assert np.isfinite(float(loss))
    gn = sum(float(jnp.sum(jnp.square(g))) for g in jax.tree.leaves(grads))
    assert gn > 0


@pytest.mark.parametrize("stage", [0, 3])
def test_trains_under_engine(stage):
    model = make_unet_model(_cfg())
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "bf16": {"enabled": False},
        "zero_optimization": {"stage": stage,
                              "stage3_param_persistence_threshold": 0},
        "steps_per_print": 1000})
    b = _batch(B=8)
    losses = [float(engine.train_batch(b)["loss"]) for _ in range(5)]
    assert losses[-1] < losses[0], losses


def test_trains_on_mesh(devices8):
    """data x tensor mesh: conv output channels column-shard over tensor."""
    model = make_unet_model(_cfg())
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "bf16": {"enabled": False},
        "zero_optimization": {"stage": 1},
        "mesh": {"axes": {"data": 4, "tensor": 2}},
        "steps_per_print": 1000}, devices=devices8)
    b = _batch(B=4)
    losses = [float(engine.train_batch(b)["loss"]) for _ in range(3)]
    assert losses[-1] < losses[0], losses


def test_inference_engine_accepts_spatial_spec():
    model = make_unet_model(_cfg())
    eng = deepspeed_tpu.init_inference(model, dtype=jnp.float32)
    b = _batch(B=2)
    out = np.asarray(eng.forward(b["x"]))
    assert out.shape == (2, 16, 16, 3)
    # timestep-conditioned through the spec's apply
    out_t = np.asarray(model.apply(eng.params, jnp.asarray(b["x"]),
                                   t=jnp.asarray(b["t"][:2])))
    assert np.isfinite(out_t).all()
