"""Progressive layer drop + eigenvalue tests (reference:
runtime/progressive_layer_drop.py, runtime/eigenvalue.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
from tests.conftest import make_batch

# quick tier: `pytest -m 'not slow'` skips this module (HVP power iteration + engine rebuilds)
pytestmark = pytest.mark.slow


class TestPLD:
    def test_engine_pld_trains_and_theta_decays(self, devices8):
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False},
            "progressive_layer_drop": {"enabled": True, "theta": 0.6,
                                       "gamma": 0.1},
            "steps_per_print": 1000})
        assert engine.model.config.progressive_layer_drop
        b = make_batch(8, 64, vocab=64)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(8)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_pld_with_grad_accumulation(self, devices8):
        """The 0-d _pld_theta side-channel must survive the microbatch
        split (regression: IndexError on scalar leaves)."""
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 64, "gradient_accumulation_steps": 4,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False},
            "progressive_layer_drop": {"enabled": True, "theta": 0.6,
                                       "gamma": 0.1},
            "steps_per_print": 1000})
        b = make_batch(64, 64, vocab=64)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(4)]
        assert np.isfinite(losses).all()

    def test_pld_eval_is_deterministic(self, devices8):
        """Eval runs all layers (no drop): identical losses across calls."""
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla",
            progressive_layer_drop=True))
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False}, "steps_per_print": 1000})
        b = make_batch(8, 64, vocab=64)
        l1 = float(engine.eval_batch(b))
        l2 = float(engine.eval_batch(b))
        assert l1 == l2

    def test_theta_one_matches_dense(self):
        """theta=1 -> keep prob 1 everywhere -> identical loss to dense."""
        from deepspeed_tpu.models.transformer import init_params, lm_loss
        cfg = TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=3, num_heads=2,
            max_seq_len=32, dtype=jnp.float32, attention_impl="xla",
            progressive_layer_drop=True)
        params = init_params(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 32)),
                          jnp.int32)
        rng = jax.random.PRNGKey(1)
        with_pld = lm_loss(params, {"input_ids": ids,
                                    "_pld_theta": jnp.float32(1.0)},
                           cfg, dropout_rng=rng, deterministic=False)
        dense = lm_loss(params, {"input_ids": ids}, cfg,
                        dropout_rng=rng, deterministic=False)
        np.testing.assert_allclose(float(with_pld), float(dense), rtol=1e-6)


class TestEigenvalue:
    def test_quadratic_exact(self):
        """Loss = 0.5 x^T A x has Hessian A; power iteration must find its
        top eigenvalue."""
        A = np.diag([5.0, 2.0, 1.0]).astype(np.float32)

        def loss(p):
            x = p["x"]
            return 0.5 * x @ jnp.asarray(A) @ x

        ev = Eigenvalue(max_iterations=50, tol=1e-4).compute_eigenvalue(
            loss, {"x": jnp.ones((3,), jnp.float32)})
        assert abs(ev - 5.0) < 0.05

    def test_blockwise(self):
        def loss(p):
            return 0.5 * (3.0 * jnp.sum(p["a"] ** 2)
                          + 7.0 * jnp.sum(p["b"] ** 2))

        evs = Eigenvalue(max_iterations=30).compute_blockwise(
            loss, {"a": jnp.ones((4,)), "b": jnp.ones((2,))})
        assert abs(evs["a"] - 3.0) < 0.1 and abs(evs["b"] - 7.0) < 0.1

    def test_bf16_params(self):
        """Probe vector must match param dtype (bf16 is the training norm)."""
        def loss(p):
            return 0.5 * 4.0 * jnp.sum(p["x"].astype(jnp.float32) ** 2)

        ev = Eigenvalue(max_iterations=30).compute_eigenvalue(
            loss, {"x": jnp.ones((8,), jnp.bfloat16)})
        assert abs(ev - 4.0) < 0.2

    def test_model_hessian_finite(self):
        from deepspeed_tpu.models.transformer import init_params, lm_loss
        cfg = TransformerConfig(
            vocab_size=32, hidden_size=16, num_layers=1, num_heads=2,
            max_seq_len=16, dtype=jnp.float32, attention_impl="xla")
        params = init_params(jax.random.PRNGKey(0), cfg)
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 32, (2, 16)),
                          jnp.int32)
        ev = Eigenvalue(max_iterations=8).compute_eigenvalue(
            lambda p: lm_loss(p, {"input_ids": ids}, cfg), params)
        assert np.isfinite(ev) and ev > 0


class TestMoQ:
    """MoQ wiring (VERDICT r3 item 9; reference: runtime/quantize.py:11 +
    engine.py:1816 eigenvalue events): start->target bits over a period,
    per-layer periods stretched by layer curvature."""

    def _model(self):
        from deepspeed_tpu.models import TransformerConfig, make_model
        return make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=32, dtype=jnp.float32, attention_impl="xla"))

    def _cfg(self, ev=False):
        return {"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                "bf16": {"enabled": False}, "steps_per_print": 1000,
                "quantize_training": {
                    "enabled": True,
                    "quantize_bits": {"start_bits": 12, "target_bits": 4},
                    "quantize_schedule": {"quantize_period": 2,
                                          "schedule_offset": 0},
                    "eigenvalue": {"enabled": ev, "max_iter": 3,
                                   "gas_boundary_resolution": 1}}}

    def test_schedule_walks_bits_down(self):
        from deepspeed_tpu.runtime.quantize import MoQ
        moq = MoQ(self._cfg()["quantize_training"], num_layers=2)
        assert moq.bits(0).tolist() == [12.0, 12.0]
        assert moq.bits(4).tolist() == [10.0, 10.0]
        assert moq.bits(100).tolist() == [4.0, 4.0]  # clipped at target
        # eigenvalue stretch: layer 1 has 3x the curvature -> longer period
        moq.update_eigenvalues(np.array([1.0, 3.0]), step=0)
        b = moq.bits(4)
        assert b[0] < b[1], b

    def test_transform_bites(self):
        """The traced transform must actually quantize (2 bits moves every
        matmul weight measurably)."""
        import jax
        from deepspeed_tpu.runtime.quantize import MoQ
        m = self._model()
        p = m.init(jax.random.PRNGKey(0))
        moq = MoQ({"quantize_bits": {"start_bits": 2, "target_bits": 2},
                   "quantize_schedule": {"quantize_period": 1}}, num_layers=2)
        pq = moq.apply(p, jnp.asarray(moq.bits(100)))
        d = max(float(jnp.abs(a - b).max()) for a, b in
                zip(jax.tree.leaves(p["layers"]),
                    jax.tree.leaves(pq["layers"])))
        assert d > 1e-3, d

    def test_trains_and_quantizes(self):
        import deepspeed_tpu
        engine, *_ = deepspeed_tpu.initialize(model=self._model(),
                                              config=self._cfg())
        assert engine._moq is not None
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, 64, (8, 32), dtype=np.int32)}
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
        assert losses[-1] < losses[0], losses

    def test_eigenvalue_refresh_updates_periods(self):
        import deepspeed_tpu
        engine, *_ = deepspeed_tpu.initialize(model=self._model(),
                                              config=self._cfg(ev=True))
        rng = np.random.default_rng(1)
        batch = {"input_ids": rng.integers(0, 64, (8, 32), dtype=np.int32)}
        engine.train_batch(batch)
        moq = engine._moq
        assert moq._last_ev_step >= 0          # refresh ran at step 0
        assert not np.allclose(moq._period_scale, 1.0)  # per-layer scales
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(3)]
        assert np.isfinite(losses).all()
