"""Copy-on-write prefix cache: refcounted blocks, chained-hash matching,
fork-on-first-write, and the latency stats that land the win (ISSUE 12).

Load-bearing contracts pinned here:

  - ``BlockAllocator`` refcounts: ``share`` increments, ``free``
    DECREMENTS and only releases at zero; the PR-9/10 guards survive
    (double free, trash block, typed out-of-range ``InvalidBlock``);
  - the cache maps full blocks by reference and partial boundary blocks
    through a copy-on-write fork (``cow_src``/``cow_dst`` at admission,
    copied before the consumer's first write);
  - a warm (cache-hit) request produces EXACTLY the cold-prefill greedy
    output — sharing is a latency lever, never a quality lever;
  - eviction under pool pressure: a full cache never blocks admission;
  - ``stats()`` now reports inter-token-latency percentiles
    (p50/p99_itl_ms) and the prefix/fork counters, and ``reset_stats``
    clears them;
  - the ``prefix-refcount-leak`` corpus entry fires on the seeded defect
    and passes on the correctly-decrementing twin.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.kv_cache import (BlockAllocator, InvalidBlock,
                                              blocks_for)
from deepspeed_tpu.inference.prefix_cache import PrefixCache
from deepspeed_tpu.inference.scheduler import RequestScheduler
from deepspeed_tpu.models import TransformerConfig, make_model


def _cfg(**overrides):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, max_seq_len=256, position_type="rotary",
                activation="silu_glu", norm_type="rmsnorm",
                tie_embeddings=False, dtype=jnp.float32,
                attention_impl="xla")
    base.update(overrides)
    return TransformerConfig(**base)


# ---------------------------------------------------------------------------
# Allocator refcounts (pure host)
# ---------------------------------------------------------------------------

class TestRefcounts:
    def test_share_then_free_decrements(self):
        a = BlockAllocator(8)
        got = a.alloc(2)
        a.share(got)
        assert all(a.refcount(b) == 2 for b in got)
        a.free(got)                       # one reader drops
        assert all(a.refcount(b) == 1 for b in got)
        assert a.used_blocks == 2         # still held by the other reader
        a.free(got)                       # last reader drops
        assert a.used_blocks == 0
        assert all(a.refcount(b) == 0 for b in got)

    def test_guards_survive_refcounting(self):
        a = BlockAllocator(8)
        got = a.alloc(1)
        a.free(got)
        with pytest.raises(ValueError, match="double free"):
            a.free(got)
        with pytest.raises(ValueError, match="trash"):
            a.free([0])
        with pytest.raises(ValueError, match="trash"):
            a.share([0])
        with pytest.raises(ValueError, match="sharing free block"):
            a.share(got)                  # stale-entry accounting bug
        with pytest.raises(InvalidBlock):
            a.free([99], owner=7)
        with pytest.raises(InvalidBlock):
            a.share([-3])

    def test_shared_block_not_reallocated(self):
        a = BlockAllocator(4)
        got = a.alloc(3)
        a.share([got[0]])
        a.free(got)                       # got[0] still referenced
        assert a.free_blocks == 2
        out = a.alloc(2)
        assert got[0] not in out


# ---------------------------------------------------------------------------
# PrefixCache (pure host)
# ---------------------------------------------------------------------------

class TestPrefixCache:
    def test_full_block_chain_match_caps_at_len_minus_one(self):
        a = BlockAllocator(32)
        c = PrefixCache(a, block_size=4)
        toks = np.arange(12, dtype=np.int32)
        blocks = a.alloc(3)
        c.insert_full(toks, blocks, rows=12)
        # identical 12-token prompt: only 2 full blocks match (cap 11 rows
        # leaves the last token to prefill — the first output token needs
        # a forward pass)
        m = c.match(toks)
        assert m.blocks == blocks[:2] and m.rows == 8
        assert m.partial_block is None
        # a diverging second block breaks the chain after block 0
        other = toks.copy()
        other[5] = 99
        m2 = c.match(other)
        assert m2.blocks == blocks[:1] and m2.rows == 4

    def test_partial_boundary_donation_and_match(self):
        a = BlockAllocator(32)
        c = PrefixCache(a, block_size=4)
        toks = np.arange(10, dtype=np.int32)       # 2 full + 2 rows
        blocks = a.alloc(3)
        c.insert_full(toks, blocks, rows=10)
        c.donate_boundary(toks, blocks, rows=10)
        assert a.refcount(blocks[2]) == 2          # cache took its ref
        # a prompt extending the donor's stream: both full blocks AND the
        # donated rows of the boundary block match
        ext = np.concatenate([toks, np.asarray([50, 51], np.int32)])
        m = c.match(ext)
        assert m.rows == 8 and m.partial_block == blocks[2]
        assert m.partial_rows == 2 and m.total_rows == 10
        # a prompt diverging INSIDE the boundary block trusts only the
        # rows that compare equal
        div = ext.copy()
        div[9] = 77
        m2 = c.match(div)
        assert m2.partial_rows == 1

    def test_eviction_cascades_and_unblocks_admission(self):
        a = BlockAllocator(8)
        c = PrefixCache(a, block_size=4)
        toks = np.arange(12, dtype=np.int32)
        blocks = a.alloc(3)
        c.insert_full(toks, blocks, rows=12)
        a.free(blocks)                             # only cache refs remain
        assert a.free_blocks == 4
        freed = c.evict(2)
        assert freed >= 2 and a.free_blocks >= 6
        # the child chain entries went with their parents: nothing matches
        assert c.match(toks).rows == 0

    def test_max_blocks_cap(self):
        a = BlockAllocator(32)
        c = PrefixCache(a, block_size=4, max_blocks=2)
        t1 = np.arange(12, dtype=np.int32)
        b1 = a.alloc(3)
        c.insert_full(t1, b1, rows=12)
        assert c.held_blocks <= 2
        t2 = 50 + np.arange(12, dtype=np.int32)
        b2 = a.alloc(3)
        c.insert_full(t2, b2, rows=12)
        assert c.held_blocks <= 2                  # LRU made room

    def test_cap_under_running_consumers_drops_only_lru(self):
        """Regression: the cap counts HELD references — when running
        requests still map the cached blocks (nothing reclaimable),
        making room for one insert must drop only the LRU entry, not
        flush the whole index chasing reclaimed-block counts."""
        a = BlockAllocator(32)
        c = PrefixCache(a, block_size=4, max_blocks=2)
        older = a.alloc(1)
        c.insert_full(np.arange(4, dtype=np.int32), older, rows=4)
        newer = a.alloc(1)
        c.insert_full(50 + np.arange(4, dtype=np.int32), newer, rows=4)
        assert c.held_blocks == 2
        # both still mapped by their "running" owners: refcount 2 each,
        # so eviction reclaims nothing to the free list
        third = a.alloc(1)
        c.insert_full(90 + np.arange(4, dtype=np.int32), third, rows=4)
        assert c.held_blocks == 2
        # the NEWER chain survived; only the LRU entry was dropped
        assert c.match(np.asarray([50, 51, 52, 53, 99], np.int32)).rows == 4
        assert c.match(np.asarray([0, 1, 2, 3, 99], np.int32)).rows == 0

    def test_clear_releases_everything(self):
        a = BlockAllocator(16)
        c = PrefixCache(a, block_size=4)
        toks = np.arange(12, dtype=np.int32)
        blocks = a.alloc(3)
        c.insert_full(toks, blocks, rows=12)
        c.donate_boundary(np.arange(10, dtype=np.int32), blocks, rows=10)
        a.free(blocks)
        c.clear()
        assert a.used_blocks == 0


# ---------------------------------------------------------------------------
# Scheduler admission: shared mapping + the CoW fork contract
# ---------------------------------------------------------------------------

class TestSchedulerSharing:
    def _sched(self, num_blocks=32, bs=4, max_seqs=4):
        alloc = BlockAllocator(num_blocks)
        cache = PrefixCache(alloc, bs)
        sched = RequestScheduler(
            alloc, max_seqs, bs, quantum=4,
            prompt_blocks=lambda n: blocks_for(max(n, bs), bs),
            max_blocks_per_seq=8, prefix_cache=cache)
        return alloc, cache, sched

    def test_admission_maps_shared_blocks_and_arms_fork(self):
        alloc, cache, sched = self._sched()
        donor_toks = np.arange(10, dtype=np.int32)
        donor = sched.submit(donor_toks, 4)
        sched.schedule()
        donor.cached_rows = 10
        sched.finish(donor)                        # publishes full+boundary
        consumer = sched.submit(
            np.concatenate([donor_toks, [60, 61, 62]]).astype(np.int32), 4)
        out = sched.schedule()
        assert out["admitted"] == [consumer]
        assert consumer.prefix_rows == 10          # 8 full + 2 boundary
        assert consumer.cached_rows == 10
        # full blocks are the DONOR's physical blocks, shared by reference
        assert consumer.block_ids[:2] == donor.block_ids[:2] \
            if donor.block_ids else True
        shared = consumer.block_ids[:2]
        assert all(alloc.refcount(b) >= 2 for b in shared)
        # the boundary block is NOT in the table — a fresh fork target is,
        # and the shared source is pinned until the engine copies it
        assert consumer.cow_src is not None
        assert consumer.cow_dst == consumer.block_ids[2]
        assert consumer.cow_src != consumer.cow_dst
        assert alloc.refcount(consumer.cow_src) >= 2

    def test_finish_decrements_shared_not_releases(self):
        alloc, cache, sched = self._sched()
        donor_toks = np.arange(8, dtype=np.int32)  # exactly 2 full blocks
        donor = sched.submit(donor_toks, 4)
        sched.schedule()
        donor.cached_rows = 8
        sched.finish(donor)
        held0 = alloc.used_blocks
        consumer = sched.submit(
            np.concatenate([donor_toks, [9, 10]]).astype(np.int32), 4)
        sched.schedule()
        consumer.cached_rows = 10
        sched._release_cow(consumer)               # engine-side fork elided
        sched.finish(consumer)
        # consumer's refs dropped; the cache's survive, plus the
        # consumer's own finish DONATED its 2-row boundary block — pool
        # ends at the cached working set, nothing double-freed or leaked
        assert alloc.used_blocks == held0 + 1
        assert alloc.used_blocks == cache.held_blocks

    def test_watermark_ignores_reclaimable_cache_blocks(self):
        """Regression: blocks held ONLY by the cache are one eviction
        from free — the pool_pressure watermark must not shed arrivals on
        an effectively empty pool (a full cache is never an admission
        loss)."""
        from deepspeed_tpu.inference.scheduler import AdmissionRejected
        alloc = BlockAllocator(17)
        cache = PrefixCache(alloc, 4)
        sched = RequestScheduler(
            alloc, 4, 4, quantum=4,
            prompt_blocks=lambda n: blocks_for(max(n, 4), 4),
            max_blocks_per_seq=8, pool_watermark=0.9, prefix_cache=cache)
        blocks = alloc.alloc(15)                   # 15/16 "used"...
        cache.insert_full(np.arange(60, dtype=np.int32), blocks, rows=60)
        alloc.free(blocks)                         # ...but all reclaimable
        assert alloc.used_fraction > 0.9
        req = sched.submit(np.arange(4, dtype=np.int32), 4)   # must NOT shed
        assert sched.schedule()["admitted"] == [req]
        # a genuinely-held pool still sheds
        sched2 = RequestScheduler(
            alloc, 4, 4, quantum=4,
            prompt_blocks=lambda n: blocks_for(max(n, 4), 4),
            pool_watermark=0.1, prefix_cache=cache)
        alloc.alloc(2)                             # real (request) usage
        with pytest.raises(AdmissionRejected, match="pool_pressure"):
            sched2.submit(np.arange(4, dtype=np.int32), 4)

    def test_blocked_admission_does_not_inflate_hit_stats(self):
        """Regression: a head-of-queue request re-matches every round its
        admission is blocked; hit stats must count per ADMISSION, not per
        retry."""
        alloc, cache, sched = self._sched(num_blocks=16, bs=4)
        donor = sched.submit(np.arange(16, dtype=np.int32), 4)
        sched.schedule()
        donor.cached_rows = 16
        sched.finish(donor)
        # block the pool so the matching consumer cannot admit
        hog = alloc.alloc(alloc.free_blocks)
        sched.submit(np.concatenate([np.arange(16), [99, 98]])
                     .astype(np.int32), 4)
        for _ in range(5):
            assert sched.schedule()["admitted"] == []
        # only the donor's own (miss) admission is on the books — the 5
        # blocked retries counted nothing
        assert cache.stats["lookups"] == 1 and cache.stats["hits"] == 0
        alloc.free(hog)
        out = sched.schedule()
        assert len(out["admitted"]) == 1
        # exactly one more lookup for the one real admission (a MISS here:
        # the blocked rounds' pressure-eviction correctly spent the cached
        # chain trying to make room — index entries drop even while the
        # match pins the blocks)
        assert cache.stats["lookups"] == 2 and cache.stats["hits"] == 0

    def test_cache_pressure_evicts_instead_of_queueing(self):
        alloc, cache, sched = self._sched(num_blocks=8)
        toks = np.arange(12, dtype=np.int32)
        donor = sched.submit(toks, 4)
        sched.schedule()
        donor.cached_rows = 12
        sched.finish(donor)                        # cache holds ~3 blocks
        # an UNRELATED prompt needing more than the uncached remainder:
        # admission must evict cache entries, not queue
        req = sched.submit(200 + np.arange(16, dtype=np.int32), 4)
        out = sched.schedule()
        assert out["admitted"] == [req]

    def test_matched_blocks_survive_admission_eviction(self):
        """Regression: admission takes its references on the matched
        blocks BEFORE pressure-eviction runs — otherwise evicting the
        matched (LRU-tail) entries would free those blocks and the LIFO
        allocator could hand them back as the SAME request's fresh write
        targets (KV aliasing), or acquire() would trip the typed
        'sharing free block' guard and fail the round."""
        alloc, cache, sched = self._sched(num_blocks=11, bs=4)
        d1 = sched.submit(np.arange(16, dtype=np.int32), 4)      # older
        sched.schedule()
        d1.cached_rows = 16
        sched.finish(d1)                           # 4 blocks cached (LRU)
        d2 = sched.submit(200 + np.arange(16, dtype=np.int32), 4)
        sched.schedule()
        d2.cached_rows = 16
        sched.finish(d2)                           # 4 more (recent)
        assert alloc.used_blocks == 8 and alloc.free_blocks == 2
        # consumer matches d1's chain (4 shared), needs 3 fresh > 2 free:
        # eviction MUST fire, and d1's chain is the LRU tail it reaches
        consumer = sched.submit(
            np.concatenate([np.arange(16), 100 + np.arange(8)])
            .astype(np.int32), 4)
        out = sched.schedule()
        assert out["admitted"] == [consumer]
        assert consumer.prefix_rows == 16          # the match survived
        ids = consumer.block_ids
        # no physical block appears twice in the table (the aliasing bug)
        assert len(ids) == len(set(ids)), ids
        assert all(alloc.refcount(b) >= 1 for b in ids)


# ---------------------------------------------------------------------------
# Engine end-to-end: warm == cold, stats, reset
# ---------------------------------------------------------------------------

def _serving(model, params, **serving):
    defaults = dict(max_seqs=2, block_size=16, max_model_len=128,
                    decode_quantum=4, prompt_bucket=16)
    defaults.update(serving)
    return deepspeed_tpu.init_serving(model, config={}, serving=defaults,
                                      dtype=jnp.float32,
                                      params=jax.device_get(params))


def _shared_load(rng, n=6, prefix=50, tail=5):
    shared = rng.integers(0, 128, size=(prefix,)).astype(np.int32)
    return [(np.concatenate([shared, rng.integers(0, 128, size=(tail,))
                             .astype(np.int32)]), 8) for _ in range(n)]


def test_warm_equals_cold_and_forks_fire():
    """The acceptance contract: an 80%-shared-prefix load served through
    the CoW cache produces EXACTLY the cold-prefill outputs, with real
    hits and real boundary forks on the books."""
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    reqs = _shared_load(np.random.default_rng(3))
    cold = _serving(model, params).run(list(reqs))
    warm_srv = _serving(model, params, enable_prefix_cache=True)
    warm = warm_srv.run(list(reqs))
    for rid in cold:
        np.testing.assert_array_equal(cold[rid], warm[rid],
                                      err_msg=f"request {rid} diverged")
    st = warm_srv.stats()
    assert st["prefix_hits"] >= 3          # later tenants rode the cache
    assert st["prefix_hit_rows"] >= 3 * 48
    assert st["cow_forks"] >= 1            # boundary blocks were copied
    assert st["prefix_hit_rate"] > 0
    # every block is either free or held by the cache — no leaked refs
    assert warm_srv.allocator.used_blocks == warm_srv._prefix_cache \
        .held_blocks


def test_full_blocks_shared_while_donor_still_running():
    """Full prompt blocks publish at PREFILL time, not at finish: a
    consumer admitted while the donor is still decoding maps them by
    reference (the agent-fleet burst case — N tenants, one system
    prompt, all in flight together)."""
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(7)
    shared = rng.integers(0, 128, size=(40,)).astype(np.int32)
    srv = _serving(model, params, enable_prefix_cache=True)
    donor = srv.add_request(shared, 60)        # long budget: stays running
    srv.step()                                 # donor prefills + decodes
    assert not srv.scheduler.done
    consumer = srv.add_request(
        np.concatenate([shared, rng.integers(0, 128, size=(4,))
                        .astype(np.int32)]), 4)
    while srv._requests[consumer].state not in ("finished", "cancelled"):
        srv.step()
    assert srv._requests[consumer].prefix_rows >= 32   # rode the donor
    assert srv._requests[donor].state == "running"     # who never finished
    while not srv.scheduler.done:
        srv.step()


def test_itl_stats_reported_and_reset():
    """Satellite 1: stats() gains p50/p99_itl_ms; reset_stats() clears
    the window (with the latency counters and the cache stats)."""
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    srv = _serving(model, params, enable_prefix_cache=True)
    rng = np.random.default_rng(0)
    srv.run([(rng.integers(0, 128, size=(12,)).astype(np.int32), 10),
             (rng.integers(0, 128, size=(20,)).astype(np.int32), 10)])
    st = srv.stats()
    assert st["p50_itl_ms"] > 0 and st["p99_itl_ms"] >= st["p50_itl_ms"]
    assert "prefix_lookups" in st and "cow_forks" in st
    srv.reset_stats()
    st2 = srv.stats()
    assert "p50_itl_ms" not in st2 and "p99_itl_ms" not in st2
    assert st2["completed"] == 0 and st2["prefix_lookups"] == 0
    assert st2["cow_forks"] == 0 and st2["prefill_chunks"] == 0


def test_preempted_consumer_resumes_warm_and_exact():
    """Preemption with shared tables in play: an oversubscribed pool
    preempts mid-load, resumes re-prefill THROUGH the cache, and every
    output still equals the cold run (the chaos-soak contract, quick)."""
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    reqs = _shared_load(np.random.default_rng(5), n=5, prefix=40, tail=7)
    cold = _serving(model, params).run(list(reqs))
    warm_srv = _serving(model, params, enable_prefix_cache=True,
                        num_blocks=12)       # below full residency
    warm = warm_srv.run(list(reqs))
    for rid in cold:
        np.testing.assert_array_equal(cold[rid], warm[rid],
                                      err_msg=f"request {rid} diverged")


@pytest.mark.slow
def test_warm_equals_cold_bf16():
    model = make_model(_cfg(dtype=jnp.bfloat16))
    params = model.init(jax.random.PRNGKey(0))
    reqs = _shared_load(np.random.default_rng(11))
    cold = deepspeed_tpu.init_serving(
        model, config={}, serving=dict(max_seqs=2, block_size=16,
                                       max_model_len=128, decode_quantum=4,
                                       prompt_bucket=16),
        params=jax.device_get(params)).run(list(reqs))
    warm = deepspeed_tpu.init_serving(
        model, config={}, serving=dict(max_seqs=2, block_size=16,
                                       max_model_len=128, decode_quantum=4,
                                       prompt_bucket=16,
                                       enable_prefix_cache=True),
        params=jax.device_get(params)).run(list(reqs))
    for rid in cold:
        np.testing.assert_array_equal(cold[rid], warm[rid],
                                      err_msg=f"request {rid} diverged")


@pytest.mark.slow
def test_warm_vs_cold_int8_kv():
    """int8-KV pools: the warm path reads the shared prefix through the
    SAME quantized blocks the donor wrote, but its residual rows are
    span-computed (float suffix reads) where the cold path prefilled —
    the same relaxation as the contiguous int8 cache's re-prefill (see
    test_serving_int8_kv_pool): prompt+first tokens exact, near-total
    agreement."""
    model = make_model(_cfg())
    reqs = _shared_load(np.random.default_rng(13), n=4)
    serving = dict(max_seqs=2, block_size=16, max_model_len=128,
                   decode_quantum=4, prompt_bucket=16)
    cold = deepspeed_tpu.init_serving(
        model, config={"kv_cache_bits": 8}, serving=serving,
        dtype=jnp.float32).run(list(reqs))
    srv = deepspeed_tpu.init_serving(
        model, config={"kv_cache_bits": 8},
        serving=dict(serving, enable_prefix_cache=True), dtype=jnp.float32)
    warm = srv.run(list(reqs))
    assert srv.pools["k"].dtype == jnp.int8
    for i, (p, _) in enumerate(reqs):
        got, ref = warm[i], cold[i]
        assert (got[:p.size + 4] == ref[:p.size + 4]).all(), (got, ref)
        assert (got == ref).mean() > 0.9


# ---------------------------------------------------------------------------
# Corpus: both directions
# ---------------------------------------------------------------------------

def test_prefix_refcount_leak_corpus_both_directions():
    from deepspeed_tpu.analysis.corpus import run_corpus
    from deepspeed_tpu.analysis.serving_lint import audit_prefix
    bad = run_corpus("prefix-refcount-leak")
    assert not bad.ok
    assert any(f.rule == "pool-growth" for f in bad.findings)
    good = audit_prefix(correct=True)
    assert good.ok, [f.message for f in good.findings]
