"""Speculative decoding + token-budget chunked prefill (ISSUE 12).

Load-bearing contracts pinned here:

  - greedy speculation is OUTPUT-PRESERVING: spec K>0 produces the exact
    greedy token stream of K=0, which is the exact stream of speculation
    off, which is the exact PR-9 one-shot ``generate()`` stream (the
    accept rule only ever emits the target model's own argmaxes);
  - the n-gram self-drafting proposer actually accepts on repetitive
    traffic (the win is real, not a no-op code path);
  - chunked prefill under a token budget slices a long prompt across
    rounds WITHOUT changing any output, and running requests keep
    decoding between the chunks (the ITL win's mechanism);
  - rejected speculation rolls the cursor back without disturbing
    refcounted/shared blocks (composed prefix-cache + spec run stays
    exact and leak-free);
  - config gates: speculation is greedy-only, and all three latency
    features refuse a model without the span protocol.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.spec_decode import (NgramProposer,
                                                 greedy_accept_len)
from deepspeed_tpu.models import TransformerConfig, make_model


def _cfg(**overrides):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, max_seq_len=256, position_type="rotary",
                activation="silu_glu", norm_type="rmsnorm",
                tie_embeddings=False, dtype=jnp.float32,
                attention_impl="xla")
    base.update(overrides)
    return TransformerConfig(**base)


def _serving(model, params, **serving):
    defaults = dict(max_seqs=2, block_size=16, max_model_len=128,
                    decode_quantum=4, prompt_bucket=16)
    defaults.update(serving)
    return deepspeed_tpu.init_serving(model, config={}, serving=defaults,
                                      dtype=jnp.float32,
                                      params=jax.device_get(params))


# ---------------------------------------------------------------------------
# Proposer + accept rule (pure host / tiny jit)
# ---------------------------------------------------------------------------

class TestNgramProposer:
    def test_matches_most_recent_occurrence(self):
        p = NgramProposer(n=2)
        ctx = np.asarray([1, 2, 9, 9, 1, 2, 7, 8, 1, 2], np.int32)
        # trailing gram (1, 2): rightmost earlier occurrence at 4 -> 7, 8
        np.testing.assert_array_equal(p.propose(ctx, 2), [7, 8])

    def test_no_match_proposes_zeros(self):
        p = NgramProposer(n=3)
        ctx = np.asarray([1, 2, 3, 4, 5], np.int32)
        np.testing.assert_array_equal(p.propose(ctx, 3), [0, 0, 0])

    def test_short_context_and_truncated_continuation(self):
        p = NgramProposer(n=4)
        assert p.propose(np.asarray([5], np.int32), 2).tolist() == [0, 0]
        # match near the end: fewer than k continuation tokens exist
        ctx = np.asarray([3, 4, 6, 3, 4], np.int32)
        np.testing.assert_array_equal(NgramProposer(2).propose(ctx, 4),
                                      [6, 3, 4, 0])

    def test_rejects_bad_n(self):
        with pytest.raises(ValueError):
            NgramProposer(0)


def test_greedy_accept_len_math():
    nxt = jnp.asarray([[5, 6, 7, 8],      # all 3 proposals right
                       [5, 6, 7, 8],      # first wrong
                       [5, 6, 7, 8]])     # second wrong
    prop = jnp.asarray([[5, 6, 7],
                        [9, 6, 7],
                        [5, 9, 7]])
    np.testing.assert_array_equal(np.asarray(greedy_accept_len(nxt, prop)),
                                  [3, 0, 1])


# ---------------------------------------------------------------------------
# Config gates
# ---------------------------------------------------------------------------

class TestConfigGates:
    def test_spec_is_greedy_only(self):
        model = make_model(_cfg())
        with pytest.raises(ValueError, match="greedy-only"):
            _serving(model, model.init(jax.random.PRNGKey(0)),
                     spec_tokens=2, temperature=0.7)

    def test_latency_features_need_span_protocol(self):
        model = make_model(_cfg())
        spanless = dataclasses.replace(model, decode_span_paged=None)
        params = model.init(jax.random.PRNGKey(0))
        for kw in (dict(spec_tokens=2), dict(enable_prefix_cache=True),
                   dict(prefill_token_budget=64)):
            with pytest.raises(ValueError, match="span protocol"):
                _serving(spanless, params, **kw)

    def test_negative_knobs_refused(self):
        model = make_model(_cfg())
        params = model.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="spec_tokens"):
            _serving(model, params, spec_tokens=-1)
        with pytest.raises(ValueError, match="prefill_token_budget"):
            _serving(model, params, prefill_token_budget=0)


# ---------------------------------------------------------------------------
# Bit-parity: spec K>0 == K=0 == off == one-shot generate()
# ---------------------------------------------------------------------------

def _repetitive_load(rng, n=3):
    """Prompts full of repeated trigrams — the self-drafting proposer's
    home turf, so acceptance is exercised for real."""
    reqs = []
    for _ in range(n):
        motif = rng.integers(0, 128, size=(4,)).astype(np.int32)
        prompt = np.concatenate([motif, motif, motif,
                                 rng.integers(0, 128, size=(3,))
                                 .astype(np.int32)])
        reqs.append((prompt, 10))
    return reqs


def test_spec_bit_parity_and_acceptance():
    """spec K=3 == spec K=0 == speculation off == PR-9 generate(), token
    for token, AND the proposer actually accepted something."""
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    reqs = _repetitive_load(np.random.default_rng(2))
    off = _serving(model, params).run(list(reqs))          # spec_tokens=0
    spec_srv = _serving(model, params, spec_tokens=3)
    on = spec_srv.run(list(reqs))
    for rid in off:
        np.testing.assert_array_equal(off[rid], on[rid],
                                      err_msg=f"request {rid} diverged")
    st = spec_srv.stats()
    assert st["spec_steps"] > 0
    assert st["spec_accepted"] > 0 and st["spec_accept_rate"] > 0
    # and the unspeculated stream is the PR-9 one-shot stream (pinned in
    # test_serving too — re-pinned here so this module stands alone)
    eng = deepspeed_tpu.init_inference(
        model, config={"kv_cache_bits": 0}, dtype=jnp.float32,
        params=jax.device_get(params))
    for i, (p, n) in enumerate(reqs):
        one = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        np.testing.assert_array_equal(off[i], one)


def test_spec_draft_hook_is_used():
    """A custom draft proposer (the draft-model hook) drives proposals;
    an oracle hook that always guesses the model's own next tokens gets
    everything accepted."""
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(4)
    reqs = [(rng.integers(0, 128, size=(9,)).astype(np.int32), 8)]
    base = _serving(model, params).run(list(reqs))
    oracle = base[0]                       # the full greedy continuation

    def draft(ctx, k):
        # next tokens after the current context, straight from the oracle
        pos = ctx.size
        return oracle[pos:pos + k]

    srv = _serving(model, params, spec_tokens=2, spec_proposer=draft)
    on = srv.run(list(reqs))
    np.testing.assert_array_equal(base[0], on[0])
    st = srv.stats()
    # an oracle draft only "misses" at the very end of the budget, where
    # it proposes past the sequence and the pads verify as wrong guesses
    assert st["spec_accept_rate"] >= 0.6
    assert st["spec_accepted"] >= 4


# ---------------------------------------------------------------------------
# Chunked prefill under a token budget
# ---------------------------------------------------------------------------

def test_chunked_prefill_exact_and_actually_chunks():
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(6)
    reqs = [(rng.integers(0, 128, size=(n,)).astype(np.int32), k)
            for n, k in ((70, 8), (9, 8), (33, 8))]
    base = _serving(model, params).run(list(reqs))
    srv = _serving(model, params, prefill_token_budget=32)
    outs = srv.run(list(reqs))
    for rid in base:
        np.testing.assert_array_equal(base[rid], outs[rid],
                                      err_msg=f"request {rid} diverged")
    st = srv.stats()
    assert st["prefill_chunks"] >= 3       # the 70-token prompt was sliced
    assert st["prefill_chunk_tokens"] >= 70


def test_decode_progresses_while_long_prompt_chunks():
    """The ITL mechanism: with a budget, a running request keeps emitting
    tokens across the rounds a 96-token admission spends prefilling —
    the long prompt no longer monopolizes whole rounds."""
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(8)
    srv = _serving(model, params, prefill_token_budget=24,
                   decode_quantum=2, max_seqs=2)
    short = srv.add_request(rng.integers(0, 128, size=(8,))
                            .astype(np.int32), 24)
    srv.step()                             # short admits + starts decoding
    long_rid = srv.add_request(rng.integers(0, 128, size=(96,))
                               .astype(np.int32), 4)
    long_req = srv._requests[long_rid]
    interleaved = 0
    for _ in range(40):
        if srv.scheduler.done:
            break
        before = len(srv._requests[short].generated)
        srv.step()
        if not long_req.prefill_done \
                and len(srv._requests[short].generated) > before:
            interleaved += 1
    assert srv.scheduler.done
    # the long admission spent >1 round prefilling AND the short request
    # gained tokens during those rounds
    assert interleaved >= 1, "decode stalled for the whole prefill"
    st = srv.stats()
    assert st["prefill_chunks"] >= 2


# ---------------------------------------------------------------------------
# Composition: cache + budget + speculation, exact and leak-free
# ---------------------------------------------------------------------------

def test_spec_at_context_cap_stays_exact():
    """A request whose prompt+budget exactly fills max_model_len decodes
    its last tokens under speculation: the verify step's overflow rows
    (proposals past the cap) must land in the trash block, not wrap into
    the slot's last block and clobber valid history (regression: the
    clipped block index used to alias position cap+i onto row i of the
    final block)."""
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(31)
    prompt = rng.integers(0, 128, size=(100,)).astype(np.int32)
    reqs = [(prompt, 28)]                     # 100 + 28 == max_model_len
    base = _serving(model, params).run(list(reqs))
    on = _serving(model, params, spec_tokens=3).run(list(reqs))
    np.testing.assert_array_equal(base[0], on[0])


def test_all_three_compose_exactly():
    model = make_model(_cfg())
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(9)
    shared = rng.integers(0, 128, size=(40,)).astype(np.int32)
    reqs = [(np.concatenate([shared, rng.integers(0, 128, size=(5,))
                             .astype(np.int32)]), 8) for _ in range(4)]
    base = _serving(model, params).run(list(reqs))
    srv = _serving(model, params, enable_prefix_cache=True,
                   prefill_token_budget=32, spec_tokens=2)
    outs = srv.run(list(reqs))
    for rid in base:
        np.testing.assert_array_equal(base[rid], outs[rid],
                                      err_msg=f"request {rid} diverged")
    st = srv.stats()
    assert st["prefix_hits"] >= 1 and st["spec_steps"] > 0
    # rejected speculation rolled cursors back WITHOUT freeing shared
    # blocks: at drain time every held block is the cache's, refcounts
    # balanced
    assert srv.allocator.used_blocks == srv._prefix_cache.held_blocks


@pytest.mark.slow
def test_spec_parity_bf16():
    model = make_model(_cfg(dtype=jnp.bfloat16))
    params = model.init(jax.random.PRNGKey(0))
    reqs = _repetitive_load(np.random.default_rng(21), n=4)
    base = deepspeed_tpu.init_serving(
        model, config={}, serving=dict(max_seqs=2, block_size=16,
                                       max_model_len=128, decode_quantum=4,
                                       prompt_bucket=16),
        params=jax.device_get(params)).run(list(reqs))
    srv = deepspeed_tpu.init_serving(
        model, config={}, serving=dict(max_seqs=2, block_size=16,
                                       max_model_len=128, decode_quantum=4,
                                       prompt_bucket=16, spec_tokens=3),
        params=jax.device_get(params))
    on = srv.run(list(reqs))
    for rid in base:
        np.testing.assert_array_equal(base[rid], on[rid],
                                      err_msg=f"request {rid} diverged")
    assert srv.stats()["spec_accepted"] > 0


@pytest.mark.slow
def test_spec_int8_kv_agreement():
    """int8 pools under speculation: the verify span reads its own fresh
    rows as floats where sequential steps re-read them quantized — same
    relaxation as the contiguous int8 cache (test_serving_int8_kv_pool):
    prompt+first tokens exact, near-total agreement."""
    model = make_model(_cfg())
    reqs = _repetitive_load(np.random.default_rng(23), n=3)
    serving = dict(max_seqs=2, block_size=16, max_model_len=128,
                   decode_quantum=4, prompt_bucket=16)
    base = deepspeed_tpu.init_serving(
        model, config={"kv_cache_bits": 8}, serving=serving,
        dtype=jnp.float32).run(list(reqs))
    srv = deepspeed_tpu.init_serving(
        model, config={"kv_cache_bits": 8},
        serving=dict(serving, spec_tokens=3), dtype=jnp.float32)
    on = srv.run(list(reqs))
    for i, (p, _) in enumerate(reqs):
        got, ref = on[i], base[i]
        assert (got[:p.size + 4] == ref[:p.size + 4]).all(), (got, ref)
        assert (got == ref).mean() > 0.9
