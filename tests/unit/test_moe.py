"""MoE tests (reference: tests/unit/moe/test_moe.py — EP groups, gating,
experts+TP interplay)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model, mixtral_config
from deepspeed_tpu.moe.sharded_moe import top_k_gating, _capacity
from tests.conftest import make_batch

# quick tier: `pytest -m 'not slow'` skips this module (EP mesh matrices compile many programs)
pytestmark = pytest.mark.slow


def moe_cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla",
                num_experts=4, top_k=2, min_capacity=4)
    base.update(kw)
    return TransformerConfig(**base)


def ds_cfg(**overrides):
    cfg = {
        "train_batch_size": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "steps_per_print": 1000,
    }
    cfg.update(overrides)
    return cfg


class TestGating:
    def test_top1_shapes_and_probs(self):
        rng = jax.random.PRNGKey(0)
        logits = jax.random.normal(rng, (32, 4))
        C = _capacity(32, 4, 1.25, 4)
        combine, dispatch, aux, metrics = top_k_gating(logits, 1, C)
        assert combine.shape == (32, 4, C)
        assert dispatch.shape == (32, 4, C)
        # each token goes to at most 1 expert slot
        per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        assert (per_token <= 1).all()
        assert float(aux) > 0

    def test_top2_combine_normalized(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (64, 4))
        C = 64  # no dropping
        combine, dispatch, aux, _ = top_k_gating(logits, 2, C)
        weights = np.asarray(jnp.sum(combine, axis=(1, 2)))
        np.testing.assert_allclose(weights, 1.0, atol=1e-5)

    def test_capacity_drops_tokens(self):
        # all tokens prefer expert 0 -> only C survive
        logits = jnp.tile(jnp.asarray([[10.0, 0, 0, 0]]), (32, 1))
        combine, dispatch, aux, metrics = top_k_gating(logits, 1, 4)
        assert int(jnp.sum(dispatch)) == 4
        assert float(metrics["dropped_fraction"]) > 0.8

    def test_capacity_positions_unique(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (64, 4))
        C = _capacity(64, 4, 1.0, 4)
        combine, dispatch, _, _ = top_k_gating(logits, 2, C)
        # no slot (e, c) may be claimed by two tokens
        slot_use = np.asarray(jnp.sum(dispatch, axis=0))
        assert (slot_use <= 1).all()


class TestMoETraining:
    def test_moe_trains(self):
        model = make_model(moe_cfg())
        engine, *_ = deepspeed_tpu.initialize(model=model, config=ds_cfg())
        batch = make_batch(16, 32, vocab=64)
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(10)]
        assert losses[-1] < losses[0]

    def test_moe_expert_parallel(self):
        """EP=4 over the expert mesh axis; experts sharded, dispatch via
        all-to-all."""
        model = make_model(moe_cfg())
        engine, *_ = deepspeed_tpu.initialize(model=model, config=ds_cfg(
            moe={"enabled": True, "expert_parallel_size": 4}))
        assert engine.plan.expert == 4
        w = engine.state["params"]["layers"]["moe_w_in"]
        flat = [a for s in w.sharding.spec if s is not None
                for a in (s if isinstance(s, tuple) else (s,))]
        assert "expert" in flat
        batch = make_batch(16, 32, vocab=64)
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
        assert losses[-1] < losses[0]

    def test_moe_ep_matches_single(self):
        """EP=4 must match EP=1 numerically (same math, different layout)."""
        model = make_model(moe_cfg())
        e1, *_ = deepspeed_tpu.initialize(model=model, config=ds_cfg())
        model2 = make_model(moe_cfg())
        e4, *_ = deepspeed_tpu.initialize(model=model2, config=ds_cfg(
            moe={"enabled": True, "expert_parallel_size": 4}))
        batch = make_batch(16, 32, vocab=64)
        l1 = [float(e1.train_batch(batch)["loss"]) for _ in range(4)]
        l4 = [float(e4.train_batch(batch)["loss"]) for _ in range(4)]
        np.testing.assert_allclose(l1, l4, rtol=2e-4, atol=1e-5)

    def test_pr_moe_residual(self):
        model = make_model(moe_cfg(use_residual=True))
        engine, *_ = deepspeed_tpu.initialize(model=model, config=ds_cfg())
        batch = make_batch(16, 32, vocab=64)
        losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
        assert losses[-1] < losses[0]

    def test_moe_with_zero3(self):
        model = make_model(moe_cfg())
        engine, *_ = deepspeed_tpu.initialize(model=model, config=ds_cfg(
            zero_optimization={"stage": 3},
            moe={"enabled": True, "expert_parallel_size": 2}))
        batch = make_batch(16, 32, vocab=64)
        m = engine.train_batch(batch)
        assert np.isfinite(float(m["loss"]))

    def test_mixtral_preset(self):
        cfg = mixtral_config("tiny", dtype=jnp.float32, attention_impl="xla",
                             max_seq_len=64)
        model = make_model(cfg)
        params = model.init(jax.random.PRNGKey(0))
        assert params["layers"]["moe_w_in"].shape[1] == 4  # experts
        loss = model.loss_fn(params, make_batch(2, 32, vocab=32000), None, True)
        assert np.isfinite(float(loss))

    def test_moe_with_tensor_parallel(self):
        """MoE inside a TP region: tokens drop/gather across the tensor
        group (reference: moe/mappings.py) — same curve as the pure-EP run."""
        model = make_model(moe_cfg())
        base, *_ = deepspeed_tpu.initialize(model=model, config=ds_cfg(
            moe={"enabled": True, "expert_parallel_size": 2}))
        batch = make_batch(16, 32, vocab=64)
        ref = [float(base.train_batch(batch)["loss"]) for _ in range(4)]

        model2 = make_model(moe_cfg())
        tp, *_ = deepspeed_tpu.initialize(model=model2, config=ds_cfg(
            moe={"enabled": True, "expert_parallel_size": 2},
            tensor_parallel={"size": 2}))
        got = [float(tp.train_batch(batch)["loss"]) for _ in range(4)]
        np.testing.assert_allclose(ref, got, rtol=2e-4, atol=1e-5)
