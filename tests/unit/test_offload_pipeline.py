"""Overlapped offload pipeline (ISSUE 14): double-buffered layer streaming
+ the three-way read(i+1) || update(i) || write(i-1) sweep under io_uring
AIO.

The pipeline is a SCHEDULING change only — every float op runs in the same
order either way — so the contract is bit-for-bit: the pipelined executor
and the fully-drained twin must produce identical metrics and identical
chunk-store bytes over 20 fp16 steps with a forced mid-run overflow (the
PR-4/8 methodology), on both the NVMe-backed and tmpfs chunk paths; and a
transient mid-step read failure injected at the nvme_*/aio_* seams must
recover through retry_io with identical numerics. The lint face
(offload-serial-pipeline / analysis.offload_lint) proves the drained shape
trips the doctor's ``offload-overlap`` gate and the pipelined twin passes.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# quick tier: pure-host doctor/plumbing checks (no engine builds)
# ---------------------------------------------------------------------------

class TestOffloadDoctor:
    """profiling/doctor.py offload attribution: the offload-overlap rule."""

    def _serial_decomp(self):
        # the drained shape: 520 ms of io on a 1000 ms step whose measured
        # compute is 480 ms — all 520 ms exposed (and dominant)
        return {"offload_compute_ms": 300.0, "offload_update_sweep_ms": 100.0,
                "offload_top_ms": 80.0, "offload_io_ms": 520.0,
                "offload_dma_ms": 400.0, "offload_pipeline": False}

    def test_gate_fires_on_serial_shape(self):
        from deepspeed_tpu.profiling.doctor import (diagnose_offload,
                                                    gate_offload)
        diag = diagnose_offload(self._serial_decomp(), step_ms=1000.0)
        assert diag["offload_compute_total_ms"] == 480.0
        assert diag["offload_exposed_io_ms"] == 520.0
        assert diag["offload_overlap_fraction"] == 0.0
        assert diag["offload_dominant_phase"] == "exposed-io-stall"
        report = gate_offload(diag)
        assert not report.ok
        (f,) = report.findings
        assert f.rule == "offload-overlap"
        assert f.data["stall"] == "host-io"
        assert "pipeline_read" in f.message

    def test_gate_passes_when_hidden(self):
        from deepspeed_tpu.profiling.doctor import (diagnose_offload,
                                                    gate_offload)
        # pipelined shape: the step barely exceeds compute — io hidden
        diag = diagnose_offload(self._serial_decomp(), step_ms=532.0)
        assert diag["offload_overlap_fraction"] == 0.9
        assert gate_offload(diag).ok
        assert not gate_offload(diag, min_overlap=0.95).ok

    def test_exposure_clamped_to_io_budget(self):
        from deepspeed_tpu.profiling.doctor import diagnose_offload
        # step way past compute + io: the excess is host overhead, not
        # storage — exposure clamps at the io budget (fraction floors at 0)
        diag = diagnose_offload(self._serial_decomp(), step_ms=5000.0)
        assert diag["offload_exposed_io_ms"] == 520.0
        assert diag["offload_overlap_fraction"] == 0.0

    def test_gate_fails_closed_when_unpriced(self):
        from deepspeed_tpu.profiling.doctor import (diagnose_offload,
                                                    gate_offload)
        # no step time anywhere: the gate must NOT certify a pipeline it
        # never measured
        diag = diagnose_offload(self._serial_decomp())
        assert "offload_overlap_fraction" not in diag
        report = gate_offload(diag)
        assert not report.ok
        assert report.findings[0].ident == "unpriced"

    def test_offload_fields_extraction(self):
        from deepspeed_tpu.profiling.doctor import (diagnose_offload,
                                                    offload_fields)
        diag = diagnose_offload(self._serial_decomp(), step_ms=1000.0)
        fields = offload_fields(diag)
        assert set(fields) == {"offload_overlap_fraction",
                               "offload_exposed_io_ms", "offload_io_ms",
                               "offload_dominant_phase"}

    def test_doctor_cli_offload_decomp(self, tmp_path):
        """CLI gate: --offload-decomp exits 1 on the serial shape, 0 on
        the hidden one."""
        bad = dict(self._serial_decomp(), offload_step_ms=1000.0)
        good = dict(self._serial_decomp(), offload_step_ms=532.0)
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
        env.pop("XLA_FLAGS", None)
        rcs = {}
        for name, decomp in (("bad", bad), ("good", good)):
            p = tmp_path / f"{name}.json"
            p.write_text(json.dumps(decomp))
            rcs[name] = subprocess.run(
                [sys.executable, "-m", "deepspeed_tpu.profiling.doctor",
                 "--offload-decomp", str(p)],
                env=env, capture_output=True, text=True).returncode
        assert rcs == {"bad": 1, "good": 0}, rcs

    def test_corpus_registry_has_offload_entry(self):
        from deepspeed_tpu.analysis.corpus import CORPUS
        assert "offload-serial-pipeline" in CORPUS


class TestAIOPlumbing:
    """Separate read/write queue depths + the aio_fallback event."""

    def test_from_config_role_depths(self):
        from deepspeed_tpu.config import AIOConfig
        from deepspeed_tpu.ops.aio import AIOHandle, aio_available
        if not aio_available():
            pytest.skip("no g++/native build")
        cfg = AIOConfig.from_dict({"block_size": 1 << 16, "queue_depth": 8,
                                   "read_queue_depth": 16,
                                   "write_queue_depth": 4})
        r = AIOHandle.from_config(cfg, "read")
        w = AIOHandle.from_config(cfg, "write")
        assert (r.queue_depth, w.queue_depth) == (16, 4)
        assert r.block_size == w.block_size == 1 << 16
        # role depths unset: both rings take the USER-set queue_depth
        cfg2 = AIOConfig.from_dict({"queue_depth": 8})
        assert AIOHandle.from_config(cfg2, "read").queue_depth == 8
        assert AIOHandle.from_config(cfg2, "write").queue_depth == 8
        # a default-constructed aio section keeps the handle's own proven
        # defaults (32/4) — wiring the config through must not silently
        # downgrade a default-config run's IO parallelism to 8/1
        cfg3 = AIOConfig.from_dict({})
        h = AIOHandle.from_config(cfg3, "read")
        assert (h.queue_depth, h.thread_count) == (32, 4)

    def test_config_pipeline_defaults_on(self):
        from deepspeed_tpu.config import AIOConfig, OffloadDeviceConfig
        off = OffloadDeviceConfig()
        assert off.pipeline_read and off.pipeline_write
        aio = AIOConfig()
        assert aio.read_queue_depth is None and aio.write_queue_depth is None

    def test_aio_fallback_event_on_unavailable(self, tmp_path, monkeypatch):
        """aio-unavailable is a STRUCTURED event through the monitor
        stream, not a one-time log line."""
        from deepspeed_tpu.robustness import events
        from deepspeed_tpu.runtime.infinity import LayerStore
        monkeypatch.setattr("deepspeed_tpu.ops.aio.aio_available",
                            lambda: False)
        events.clear()
        store = LayerStore(str(tmp_path), n_layers=1, chunk_elems=128,
                           backend="nvme")
        try:
            recs = events.history("aio_fallback")
            assert recs and recs[-1]["component"] == "infinity-layer-store"
        finally:
            store.close()
            events.clear()


# ---------------------------------------------------------------------------
# slow tier: engine-level parity / fault recovery / corpus twins
# ---------------------------------------------------------------------------

def _cfg_dict(tmp, pipeline: bool, *, use_cpu_adam: bool = False,
              scale_power: int = 8):
    return {
        "train_batch_size": 4,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-3, "weight_decay": 0.01}},
        "fp16": {"enabled": True, "initial_scale_power": scale_power,
                 "hysteresis": 1},
        "bf16": {"enabled": False},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "nvme", "nvme_path": str(tmp),
                              "pipeline_read": pipeline,
                              "pipeline_write": pipeline},
            "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp),
                                  "use_cpu_adam": use_cpu_adam,
                                  "pipeline_read": pipeline,
                                  "pipeline_write": pipeline},
        },
        "steps_per_print": 1000000,
    }


def _model():
    # deliberately small: fp16 compute is SOFTWARE-emulated on CPU XLA
    # (~100x slower than bf16 at llama-tiny size) and the parity contract
    # is about SCHEDULING, not model scale — hidden-64 exercises the exact
    # same executor code paths at a wall cost the slow tier can afford
    from deepspeed_tpu.models import TransformerConfig, make_model
    return make_model(TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
        max_seq_len=64, attention_impl="xla", loss_chunk=32), name="tiny")


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, (4, 64), dtype=np.int32)}


def _run_steps(engine, nsteps=20, poke_at=7):
    """nsteps fp16 steps; at step ``poke_at`` the loss scale is poked to
    2^24 (the PR-8 methodology for token-id inputs) forcing a
    deterministic overflow burst + recovery on both arms."""
    ex = engine._infinity_exec
    out = []
    for s in range(nsteps):
        if s == poke_at:
            ex._scale = 2.0 ** 24
        m = engine.train_batch(_batch(seed=s))
        out.append((float(m["loss"]), float(m["grad_norm"]),
                    bool(m["overflow"]), float(m["loss_scale"])))
    return out


def _store_bytes(ex):
    """Every layer's param bits + opt chunk, fetched from the store."""
    out = []
    for i in range(ex.cfg.num_layers):
        out.append(np.asarray(ex.store.read_param(i)).copy())
        opt = ex.store.read_opt(i)
        out.append(None if opt is None else np.asarray(opt).copy())
    return out


@pytest.mark.slow
class TestPipelineParity:
    """Pipelined vs fully-drained offload is bit-for-bit identical: same
    per-step metrics (incl. the forced-overflow skip/rescale) and the same
    chunk-store bytes, on NVMe-backed and tmpfs paths, for both the
    device-Adam and native host-Adam sweeps."""

    def _parity(self, root_a, root_b, use_cpu_adam):
        import deepspeed_tpu
        if use_cpu_adam:
            from deepspeed_tpu.ops.cpu_adam import cpu_adam_available
            if not cpu_adam_available():
                pytest.skip("native cpu_adam toolchain unavailable")
        e1, *_ = deepspeed_tpu.initialize(
            model=_model(),
            config=_cfg_dict(root_a, True, use_cpu_adam=use_cpu_adam))
        e2, *_ = deepspeed_tpu.initialize(
            model=_model(),
            config=_cfg_dict(root_b, False, use_cpu_adam=use_cpu_adam))
        assert e1._infinity_exec.pipeline is True
        assert e2._infinity_exec.pipeline is False
        m1 = _run_steps(e1)
        m2 = _run_steps(e2)
        # exact float equality, NaN-aware (the overflow step's grad_norm
        # is NaN by contract and NaN != NaN under tuple equality)
        np.testing.assert_array_equal(np.asarray(m1, np.float64),
                                      np.asarray(m2, np.float64))
        # the overflow burst actually happened (else the test proves less)
        assert any(o for _, _, o, _ in m1)
        assert any(not o for _, _, o, _ in m1[8:])
        s1, s2 = _store_bytes(e1._infinity_exec), _store_bytes(
            e2._infinity_exec)
        for a, b in zip(s1, s2):
            if a is None or b is None:
                assert a is None and b is None
            else:
                np.testing.assert_array_equal(a, b)
        e1._infinity_exec.close()
        e2._infinity_exec.close()

    def test_nvme_device_adam(self, tmp_path):
        self._parity(tmp_path / "a", tmp_path / "b", use_cpu_adam=False)

    def test_nvme_native_host_adam(self, tmp_path):
        self._parity(tmp_path / "a", tmp_path / "b", use_cpu_adam=True)

    def test_tmpfs_native_host_adam(self, tmp_path):
        shm = "/dev/shm"
        if not (os.path.isdir(shm) and os.access(shm, os.W_OK)):
            pytest.skip("no writable tmpfs at /dev/shm")
        import tempfile
        root = tempfile.mkdtemp(dir=shm, prefix="dstpu-offload-")
        try:
            self._parity(os.path.join(root, "a"), os.path.join(root, "b"),
                         use_cpu_adam=True)
        finally:
            import shutil
            shutil.rmtree(root, ignore_errors=True)


@pytest.mark.slow
class TestFaultRecovery:
    """A transient mid-step read failure at the nvme_*/aio_* seams
    recovers through retry_io with numerics identical to the fault-free
    run (and a fault_recovered event on the stream)."""

    def test_mid_step_read_fault_recovers_identically(self, tmp_path):
        import deepspeed_tpu
        from deepspeed_tpu.robustness import events, faults
        ref, *_ = deepspeed_tpu.initialize(
            model=_model(), config=_cfg_dict(tmp_path / "ref", True))
        m_ref = _run_steps(ref, nsteps=6, poke_at=99)
        ref._infinity_exec.close()

        events.clear()
        # whichever read path is active fires (aio_read with the native
        # build, nvme_read on the numpy fallback); times=2 < retry_io's 4
        # attempts, so the fault is transient and MUST be absorbed
        sched = faults.FaultSchedule([
            {"kind": "io_error", "op": "aio_read", "at": 3, "times": 2,
             "errno": "EIO"},
            {"kind": "io_error", "op": "nvme_read", "at": 3, "times": 2,
             "errno": "EIO"},
        ])
        injector = faults.install(faults.FaultInjector(sched))
        try:
            got, *_ = deepspeed_tpu.initialize(
                model=_model(), config=_cfg_dict(tmp_path / "got", True))
            m_got = _run_steps(got, nsteps=6, poke_at=99)
            got._infinity_exec.close()
            assert injector.fired, "scheduled read fault never fired"
            assert events.history("fault_recovered"), \
                "transient read fault was not retried"
        finally:
            faults.install(None)
            events.clear()
        assert m_got == m_ref, (m_got, m_ref)


@pytest.mark.slow
class TestOffloadCorpusTwins:
    """offload-serial-pipeline: the drained executor trips the doctor's
    offload-overlap gate (host-stall dominant); the pipelined twin passes.
    (CLI: python -m deepspeed_tpu.analysis.offload_lint [--pipelined];
    seeded via analysis.lint --corpus offload-serial-pipeline.)"""

    def test_serial_fires(self):
        from deepspeed_tpu.analysis.offload_lint import audit_offload
        report = audit_offload(pipeline=False)
        assert not report.ok
        assert {f.rule for f in report.findings} == {"offload-overlap"}
        (f,) = report.findings
        assert f.data["stall"] == "host-io"
        assert f.data["offload_overlap_fraction"] < 0.5

    def test_pipelined_twin_passes(self):
        from deepspeed_tpu.analysis.offload_lint import audit_offload
        report = audit_offload(pipeline=True)
        assert report.ok, [f.message for f in report.findings]


@pytest.mark.slow
class TestSwapperPipeline:
    """NVMeOptimizerSwapper: the double-buffered write-behind + separate
    read/write rings change nothing numerically vs the drained swapper."""

    def test_pipelined_vs_drained_identical(self, tmp_path):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from deepspeed_tpu.runtime.swap_tensor import NVMeOptimizerSwapper
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
        tmpl = {"w": jnp.zeros((256, 128), jnp.float32),
                "b": jnp.zeros((97,), jnp.float32)}
        rng = np.random.default_rng(0)
        params = {"w": jnp.asarray(rng.standard_normal((256, 128)),
                                   jnp.float32),
                  "b": jnp.asarray(rng.standard_normal((97,)), jnp.float32)}
        grads = {"w": jnp.asarray(rng.standard_normal((256, 128)),
                                  jnp.float32),
                 "b": jnp.asarray(rng.standard_normal((97,)), jnp.float32)}

        def run(pipeline):
            root = tmp_path / ("pipe" if pipeline else "drained")
            root.mkdir(exist_ok=True)
            sw = NVMeOptimizerSwapper(
                tmpl, mesh=mesh, nvme_path=str(root),
                chunk_elems=4096,    # several chunks: the pipeline engages
                compute_dtype=jnp.float32, pipeline=pipeline)
            sw.initialize(params)
            p = params
            for s in range(1, 4):
                p, gnorm, ovf = sw.step(grads, lr=1e-3, step_num=s)
                assert not ovf
            state = sw.export_state()
            sw.close()
            return p, gnorm, state

        p1, g1, s1 = run(True)
        p2, g2, s2 = run(False)
        assert g1 == g2
        for k in p1:
            np.testing.assert_array_equal(np.asarray(p1[k]),
                                          np.asarray(p2[k]))
        for k in s1:
            np.testing.assert_array_equal(s1[k], s2[k])
