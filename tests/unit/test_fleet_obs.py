"""Fleet observability (ISSUE 18): request tracing, serving doctor, rollup.

The three contracts pinned here:

  - tracing is FREE where it counts: a tracing-armed engine produces
    BIT-IDENTICAL outputs to an untraced one (zero added device syncs,
    self-reported through ``tracer.device_syncs``), and a 2-replica
    failover under tracing stays bit-identical to the fault-free run
    while the merged Chrome trace shows ONE trace id spanning both
    replica process rows (drain-state v3 stitching);
  - the serving doctor prices the round-phase decomposition fail-closed
    and names the dominant phase with a knob (``serving-blind-stall`` /
    ``tracing-sync-leak`` corpus twins, both directions);
  - the router's fleet rollup is exactly the sum of per-replica truth,
    survives the Prometheus text round-trip, and ``reset_stats`` clears
    every counter it exposes (the PR-12 pinned-reset contract at fleet
    scope).
"""

import collections
import json
import os

import numpy as np
import pytest

from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.telemetry.exposition import (DEFAULT_EDGES_MS, Histogram,
                                                parse_exposition,
                                                render_prometheus)
from deepspeed_tpu.telemetry.request_trace import (RequestTracer,
                                                   merge_chrome_trace)


@pytest.fixture(autouse=True)
def _clean_robustness_state():
    rb_faults.clear()
    rb_events.clear()
    yield
    rb_faults.clear()
    rb_events.clear()


# ---------------------------------------------------------------------------
# RequestTracer (pure host)
# ---------------------------------------------------------------------------

class TestRequestTracer:
    def test_begin_idempotent_and_sequenced(self):
        tr = RequestTracer(replica="rA")
        tid = tr.begin(7)
        assert tid == "rA/7.0"
        assert tr.begin(7) == tid              # re-begin keeps the id
        assert tr.begin(8) == "rA/8.1"         # fresh rid, next seq
        tr.end(7)
        assert tr.trace_id(7) is None
        assert tr.begin(7) == "rA/7.2"         # resubmission = new trace

    def test_span_context_adopt_stitch(self):
        """The migration stitching rule end to end: the destination
        inherits the trace id and re-appends the source's spans with
        their ORIGINAL replica tags, so one merged export shows the
        request in two process rows under one trace id."""
        src = RequestTracer(replica="r0")
        tid = src.begin(3)
        with src.span(3, "prefill", tokens=4):
            pass
        src.instant(3, "drained", tag="t")
        ctx = src.context(3)
        assert ctx["id"] == tid
        assert [e["name"] for e in ctx["spans"]] == ["prefill", "drained"]

        dst = RequestTracer(replica="r1")
        assert dst.adopt(3, ctx) == tid        # id survives migration
        dst.instant(3, "migrated_in")
        with dst.span(3, "decode_quantum"):
            pass
        # history keeps r0's tag; new activity is tagged r1
        reps = [e["replica"] for e in dst.events]
        assert reps == ["r0", "r0", "r1", "r1"]
        assert all(e["trace"] == tid for e in dst.events)

        merged = merge_chrome_trace([dst.export()])
        evs = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        pids = {e["pid"] for e in evs}
        assert len(pids) == 2                  # two process rows
        assert {e["args"]["trace"] for e in evs} == {tid}
        names = {e["name"] for e in merged["traceEvents"] if e["ph"] == "M"}
        assert names == {"process_name"}

    def test_adopt_empty_ctx_begins_fresh(self):
        tr = RequestTracer(replica="r1")
        assert tr.adopt(5, None) == "r1/5.0"   # v2 record: no trace ctx

    def test_ring_bounded(self):
        tr = RequestTracer(replica="r0", max_events=64)
        tr.begin(1)
        for i in range(500):
            tr.instant(1, f"e{i}")
        assert len(tr.events) == 64
        assert tr.events[-1]["name"] == "e499"

    def test_leaky_hook_is_self_reported(self):
        """The documented defect seam: whatever on_span does is on the
        caller, and the sync count it self-reports is the evidence the
        doctor's tracing-sync-leak gate prices."""
        tr = RequestTracer(replica="r0")

        def leaky(ev):
            tr.device_syncs += 1

        tr.on_span = leaky
        tr.begin(1)
        tr.instant(1, "a")
        with tr.span(1, "b"):
            pass
        assert tr.device_syncs == 2
        # adopted history is NOT new activity: the hook must not fire
        tr2 = RequestTracer(replica="r1", on_span=leaky)
        tr2.adopt(1, tr.context(1))
        assert tr.device_syncs == 2


# ---------------------------------------------------------------------------
# Histogram + exposition (pure host)
# ---------------------------------------------------------------------------

class TestExposition:
    def test_merge_requires_matching_edges(self):
        a, b = Histogram([1, 2, 4]), Histogram([1, 2, 4])
        a.observe_many([0.5, 3.0, 100.0])      # under, mid, overflow
        b.observe(1.5)
        a.merge(b)
        assert a.count == 4 and a.counts[-1] == 1   # overflow bucket
        with pytest.raises(ValueError):
            a.merge(Histogram([1, 2, 8]))

    def test_from_dict_rejects_malformed(self):
        h = Histogram([1, 2])
        h.observe(1.5)
        rt = Histogram.from_dict(h.to_dict())
        assert rt is not None and rt.counts == h.counts
        # version-skew rule: malformed payloads are ignored, not fatal
        assert Histogram.from_dict(None) is None
        assert Histogram.from_dict({"edges": [1, 2]}) is None
        assert Histogram.from_dict({"edges": [1], "counts": [1]}) is None

    def test_render_parse_roundtrip(self):
        h = Histogram(DEFAULT_EDGES_MS)
        h.observe_many([0.5, 3.0, 3.5, 900.0, 1e6])
        text = render_prometheus({"ttft_ms": h, "live": 2,
                                  "ok": True}, prefix="dstpu")
        assert "# TYPE dstpu_ttft_ms histogram" in text
        assert 'le="+Inf"' in text
        parsed = parse_exposition(text)
        assert parsed["dstpu_live"] == 2.0
        assert parsed["dstpu_ok"] == 1.0
        back = parsed["dstpu_ttft_ms"]
        assert back.count == h.count and back.counts == h.counts
        assert back.sum == pytest.approx(h.sum)

    def test_quantile_upper_edge(self):
        h = Histogram([1, 2, 4, 8])
        h.observe_many([1.5] * 9 + [7.0])
        assert h.quantile(0.5) == 2.0          # upper edge of the bucket
        assert h.quantile(0.99) == 8.0
        assert Histogram([1, 2]).quantile(0.5) == 0.0   # empty window


# ---------------------------------------------------------------------------
# Round-phase ring + stall event (host rig over the REAL methods)
# ---------------------------------------------------------------------------

def _entry(round_ms=1.0, **phases):
    e = {"schedule_ms": 0.1, "housekeeping_ms": 0.1, "prefill_ms": 0.1,
         "decode_ms": 0.2, "fetch_ms": 0.3, "commit_ms": 0.1,
         "round_ms": round_ms, "tokens": 8.0}
    e.update(phases)
    return e


class _PhaseRig:
    """The ServingEngine phase-ring surface, host-only: the REAL
    ``_note_phases`` / ``phase_decomposition`` bound to a stub so the
    stall-event state machine is pinned without a jit compile."""
    from deepspeed_tpu.inference.serving import ServingEngine as _SE
    _STALL_MIN_ROUND_MS = _SE._STALL_MIN_ROUND_MS
    _STALL_FRACTION = _SE._STALL_FRACTION
    _note_phases = _SE._note_phases
    phase_decomposition = _SE.phase_decomposition

    def __init__(self, warm=True):
        self._phases = collections.deque(maxlen=256)
        self._quantum_warm = warm
        self._phase_stall_events = 0
        self._tracer = None


class TestPhaseStallEvent:
    def test_stall_fires_once_naming_the_phase(self):
        rig = _PhaseRig()
        for _ in range(9):
            rig._note_phases(_entry())
        rig._note_phases(_entry(round_ms=200.0, housekeeping_ms=150.0))
        evs = rb_events.history("serving_phase_stall")
        assert len(evs) == 1
        assert evs[0]["phase"] == "housekeeping"
        assert evs[0]["round_ms"] == pytest.approx(200.0)
        # latched: a second stall in the same window does not re-emit
        rig._note_phases(_entry(round_ms=300.0, housekeeping_ms=250.0))
        assert len(rb_events.history("serving_phase_stall")) == 1
        assert rig.phase_decomposition()["serve_phase_stall_events"] == 1.0

    def test_fetch_dominance_is_exempt(self):
        """Fetch-bound means the accelerator is the bottleneck — health,
        not a stall."""
        rig = _PhaseRig()
        for _ in range(9):
            rig._note_phases(_entry())
        rig._note_phases(_entry(round_ms=200.0, fetch_ms=190.0))
        assert rb_events.history("serving_phase_stall") == []

    def test_cold_engine_and_thin_baseline_stay_quiet(self):
        cold = _PhaseRig(warm=False)
        for _ in range(12):
            cold._note_phases(_entry(round_ms=200.0, housekeeping_ms=150.0))
        assert rb_events.history("serving_phase_stall") == []
        thin = _PhaseRig()                     # warm but < 9 rounds of
        for _ in range(5):                     # baseline: compile noise
            thin._note_phases(_entry(round_ms=200.0, housekeeping_ms=150.0))
        assert rb_events.history("serving_phase_stall") == []

    def test_decomposition_sums_the_ring(self):
        rig = _PhaseRig()
        for _ in range(4):
            rig._note_phases(_entry())
        d = rig.phase_decomposition()
        assert d["serve_rounds"] == 4.0
        assert d["serve_tokens"] == 32.0
        assert d["serve_fetch_ms"] == pytest.approx(1.2)
        assert d["trace_armed"] == 0.0 and d["trace_device_syncs"] == 0.0


# ---------------------------------------------------------------------------
# Serving doctor (host-only)
# ---------------------------------------------------------------------------

class TestServingDoctor:
    def test_blind_stall_corpus_both_directions(self):
        from deepspeed_tpu.profiling import doctor
        bad = doctor.audit_serving(stalled=True)
        assert not bad.ok
        f = next(f for f in bad.findings if f.rule == "serving-phase-stall")
        assert "paging-bound" in f.message       # the bound is named
        assert "adapter_slots" in f.message      # ... with a knob
        good = doctor.audit_serving(stalled=False)
        assert good.ok and good.findings == []

    def test_sync_leak_corpus_both_directions(self):
        from deepspeed_tpu.profiling import doctor
        bad = doctor.audit_tracing(leaky=True)
        assert not bad.ok
        f = next(f for f in bad.findings if f.rule == "tracing-sync-leak")
        assert f.ident == "device-syncs"
        assert doctor.audit_tracing(leaky=False).ok

    def test_gate_fails_closed_when_unpriced(self):
        from deepspeed_tpu.profiling import doctor
        r = doctor.gate_serving(doctor.diagnose_serving({}))
        assert not r.ok and r.findings[0].ident == "unpriced"

    def test_diagnose_attributes_bound_and_top2(self):
        from deepspeed_tpu.profiling import doctor
        d = doctor.diagnose_serving(doctor.simulate_serving_decomp())
        assert d["serve_bound"] == "fetch-bound"
        top2 = d["serve_phase_top2"]
        assert [p["phase"] for p in top2] == ["fetch", "decode_dispatch"]
        assert top2[0]["fraction"] > top2[1]["fraction"]
        fields = doctor.serving_fields(d)
        assert set(fields) == {"serve_bound", "serve_dominant_phase",
                               "serve_phase_top2", "serve_ms_per_token"}

    def test_corpus_registry_wiring(self):
        """Both twins ride the shared corpus registry (lint --corpus)."""
        from deepspeed_tpu.analysis.corpus import CORPUS
        assert "serving-blind-stall" in CORPUS
        assert "tracing-sync-leak" in CORPUS


# ---------------------------------------------------------------------------
# Engine end-to-end: tracing bit-parity + drain-v3 stitching
# ---------------------------------------------------------------------------

def _tiny_model():
    import jax.numpy as jnp
    from deepspeed_tpu.models import TransformerConfig, make_model
    return make_model(TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=1, num_heads=4,
        num_kv_heads=2, max_seq_len=64, position_type="rotary",
        activation="silu_glu", norm_type="rmsnorm", tie_embeddings=False,
        dtype=jnp.float32, attention_impl="xla"))


def _serving(model, params=None, **kw):
    import jax.numpy as jnp
    import deepspeed_tpu
    d = dict(max_seqs=2, block_size=16, max_model_len=64, decode_quantum=2,
             prompt_bucket=16, decode_backend="xla")
    d.update(kw)
    return deepspeed_tpu.init_serving(model, config={}, serving=d,
                                      dtype=jnp.float32, params=params)


def _load(seed=0, n=3):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, 128, size=(int(k),)).astype(np.int32), int(m))
            for k, m in zip(rng.integers(4, 14, n), rng.integers(3, 6, n))]


class TestTracedEngineParity:
    def test_tracing_on_off_bit_identical(self):
        """The zero-sync contract: same params, same load — the traced
        engine's outputs are byte-for-byte the untraced engine's, the
        tracer self-reports zero device syncs, and the lifecycle spans
        are all present."""
        import jax
        model = _tiny_model()
        reqs = _load()
        plain = _serving(model)
        base = plain.run(list(reqs))
        params = jax.device_get(plain.engine.params)

        traced = _serving(model, params=params, request_trace=True,
                          trace_replica="rA")
        outs = traced.run(list(reqs))
        for i in base:
            np.testing.assert_array_equal(base[i], outs[i])
        tr = traced.tracer
        assert tr is not None and tr.device_syncs == 0
        names = {e["name"] for e in tr.events}
        assert {"admitted", "queue_wait", "prefill", "decode_quantum",
                "finish"} <= names
        assert all(e.get("replica") == "rA" for e in tr.events)
        # every request got one trace id, admission through finish
        per_rid = collections.defaultdict(set)
        for e in tr.events:
            per_rid[e["rid"]].add(e["trace"])
        assert len(per_rid) == len(reqs)
        assert all(len(tids) == 1 for tids in per_rid.values())

        d = traced.phase_decomposition()
        assert d["serve_rounds"] > 0 and d["serve_tokens"] > 0
        assert d["trace_armed"] == 1.0 and d["trace_device_syncs"] == 0.0

        meta = traced.obs_meta()
        assert meta["completed"] == len(reqs)
        assert Histogram.from_dict(meta["ttft_ms_hist"]).count == len(reqs)

        # pinned reset, fleet scope: every exposed counter clears
        traced.reset_stats()
        d = traced.phase_decomposition()
        assert d["serve_rounds"] == 0.0 and d["serve_tokens"] == 0.0
        assert d["serve_phase_stall_events"] == 0.0
        meta = traced.obs_meta()
        assert meta["completed"] == 0 and meta["generated_tokens"] == 0
        assert Histogram.from_dict(meta["ttft_ms_hist"]).count == 0
        assert Histogram.from_dict(meta["itl_ms_hist"]).count == 0

    def test_drain_v3_carries_trace_and_v2_interops(self, tmp_path):
        """Drain-state v3: each record carries the trace context and the
        drain marker rides it; adoption on the destination preserves the
        id. A v2 record (no "trace" key) still restores."""
        model = _tiny_model()
        src = _serving(model, request_trace=True, trace_replica="r0")
        for p, k in _load(seed=1, n=2):
            src.add_request(p, k)
        tag_dir = src.drain(str(tmp_path), tag="t0", source="r0")
        state = json.load(open(os.path.join(tag_dir, "state.json")))
        assert state["version"] == 3
        assert len(state["requests"]) == 2
        for rec in state["requests"]:
            ctx = rec["trace"]
            assert ctx["id"].startswith("r0/")
            names = [e["name"] for e in ctx["spans"]]
            assert "admitted" in names and names[-1] == "drained"

        import jax
        dst = _serving(model, params=jax.device_get(src.engine.params),
                       request_trace=True, trace_replica="r1")
        recs = state["requests"]
        recs[1] = {k: v for k, v in recs[1].items() if k != "trace"}  # v2
        rids = dst.accept_migration(recs, rng_counter=state["rng_counter"],
                                    source="r0",
                                    geometry=state["engine"])
        assert len(rids) == 2
        assert dst.tracer.trace_id(rids[0]) == state["requests"][0][
            "trace"]["id"]                     # stitched
        assert dst.tracer.trace_id(rids[1]).startswith("r1/")   # fresh
        ev_names = [e["name"] for e in dst.tracer.events
                    if e["rid"] == rids[0]]
        assert "migrated_in" in ev_names and "drained" in ev_names


# ---------------------------------------------------------------------------
# Router: fleet rollup + traced failover stitching
# ---------------------------------------------------------------------------

def _router(tmp_path, clock, **kw):
    from deepspeed_tpu.inference.router import RouterConfig, ServingRouter
    cfg = RouterConfig(store_dir=str(tmp_path / "store"),
                       drain_dir=str(tmp_path / "drains"),
                       dead_after_s=2.0, clock=clock, **kw)
    return ServingRouter(cfg)


def _drive(router, reqs, t):
    from deepspeed_tpu.inference.scheduler import AdmissionRejected
    pending = collections.deque(reqs)
    outs, rounds = {}, 0
    while pending or not router.done:
        while pending:
            p, k = pending[0]
            try:
                router.add_request(p, k)
            except AdmissionRejected:
                break
            pending.popleft()
        for r in router.step():
            outs[r.rid] = r.output
        t[0] += 1.0
        rounds += 1
        assert rounds < 200, "router test did not converge"
    return outs


@pytest.mark.slow
class TestFleetRollup:
    def test_rollup_matches_per_replica_truth_and_resets(self, tmp_path):
        import jax
        model = _tiny_model()
        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        e0 = _serving(model, max_queue=4)
        e1 = _serving(model, params=jax.device_get(e0.engine.params),
                      max_queue=4)
        router.register("r0", e0)
        router.register("r1", e1)
        _drive(router, _load(seed=2, n=5), t)

        fs = router.fleet_stats()
        truth = [e0.obs_meta(), e1.obs_meta()]
        assert fs["fleet_replicas"] == 2 and fs["fleet_live"] == 2
        for key in ("completed", "cancelled", "generated_tokens"):
            assert fs[f"fleet_{key}"] == sum(m[key] for m in truth), key
        assert fs["fleet_completed"] == 5
        # merged histogram = per-replica histograms, bucket for bucket
        want = Histogram(DEFAULT_EDGES_MS)
        for m in truth:
            want.merge(Histogram.from_dict(m["ttft_ms_hist"]))
        assert fs["fleet_ttft_ms"].counts == want.counts
        assert fs["fleet_ttft_ms"].count == 5
        # gauges cover the live fleet
        assert fs["fleet_queue_depth"].count == 2
        assert fs["fleet_pool_occupancy"].count == 2

        # scrape round-trip: text exposition reconstructs the rollup
        parsed = parse_exposition(router.exposition(prefix="dstpu"))
        assert parsed["dstpu_fleet_completed"] == 5.0
        assert parsed["dstpu_fleet_ttft_ms"].counts == want.counts
        assert parsed["dstpu_fleet_live"] == 2.0

        # pinned reset at FLEET scope: every rollup counter clears
        router.reset_stats()
        fs = router.fleet_stats()
        assert fs["fleet_completed"] == 0 and fs["fleet_generated_tokens"] \
            == 0
        assert fs["fleet_ttft_ms"].count == 0
        assert fs["fleet_itl_ms"].count == 0
        assert fs["fleet_live"] == 2           # liveness is not history

    def test_traced_failover_bit_identical_and_stitched(self, tmp_path):
        """The acceptance gate: a 2-replica fleet with tracing armed,
        replica 0 killed mid-load — outputs bit-identical to a fault-free
        untraced single-replica run, and the merged Chrome trace shows
        the migrated requests' ids spanning BOTH replica process rows."""
        import jax
        from deepspeed_tpu.robustness.faults import (FaultInjector,
                                                     FaultSchedule)
        model = _tiny_model()
        reqs = _load(seed=3, n=6)
        plain = _serving(model, max_seqs=4)
        base = plain.run(list(reqs))
        params = jax.device_get(plain.engine.params)

        t = [0.0]
        router = _router(tmp_path, clock=lambda: t[0])
        e0 = _serving(model, params=params, max_queue=4, request_trace=True)
        e1 = _serving(model, params=params, max_queue=4, request_trace=True)
        router.register("r0", e0)
        router.register("r1", e1)
        # register() retags each engine's default-"r0" tracer to its
        # replica name — otherwise both streams land on one process row
        assert e0.tracer.replica == "r0" and e1.tracer.replica == "r1"
        rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "replica_kill", "at": 2, "replica": 0},
        ], seed=0)))
        outs = _drive(router, reqs, t)

        st = router.stats()
        assert st["failovers"] == 1.0 and st["migrated"] >= 1.0
        assert st["lost_requests"] == 0.0
        assert set(outs) == set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged under tracing")
        assert e0.tracer.device_syncs == 0 and e1.tracer.device_syncs == 0

        merged = merge_chrome_trace(
            [e0.tracer.export(), e1.tracer.export()],
            path=str(tmp_path / "fleet.json"))
        evs = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        trace_pids = collections.defaultdict(set)
        for e in evs:
            trace_pids[e["args"]["trace"]].add(e["pid"])
        spanning = [tid for tid, pids in trace_pids.items()
                    if len(pids) >= 2]
        assert spanning, "no trace id spans both replica process rows"
        # the on-disk merge emitted its export event
        assert json.load(open(tmp_path / "fleet.json"))["traceEvents"]
        assert rb_events.history("trace_export")
