"""Ring attention / sequence parallelism tests (no reference equivalent —
SURVEY §2.7 notes SP is absent there; first-class here)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.ops.ring_attention import ring_attention
from deepspeed_tpu.ops.flash_attention import reference_attention
from deepspeed_tpu.parallel import MeshPlan, build_mesh
from tests.conftest import make_batch


@pytest.fixture()
def seq_mesh(devices8):
    return build_mesh(MeshPlan(seq=4, data=2))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(seq_mesh, causal):
    B, S, N, D = 2, 64, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, S, N, D))
    k = jax.random.normal(ks[1], (B, S, N, D))
    v = jax.random.normal(ks[2], (B, S, N, D))
    out = ring_attention(q, k, v, seq_mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_seq_parallel_training_matches_dp():
    """sp=4: same losses as pure dp (sequence layout is invisible to math)."""
    def run(cfg_overrides):
        from deepspeed_tpu.parallel.context import set_parallel_context
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
        config = {
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False}, "steps_per_print": 1000,
        }
        config.update(cfg_overrides)
        engine, *_ = deepspeed_tpu.initialize(model=model, config=config)
        batch = make_batch(8, 32, vocab=64)
        return [float(engine.train_batch(batch)["loss"]) for _ in range(5)]

    base = run({})
    sp = run({"sequence_parallel": {"size": 4}})
    np.testing.assert_allclose(base, sp, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_seq_parallel_with_zero3():
    from deepspeed_tpu.models import TransformerConfig, make_model
    model = make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "sequence_parallel": {"size": 2},
        "zero_optimization": {"stage": 3},
        "steps_per_print": 1000})
    assert engine.plan.seq == 2 and engine.plan.fsdp == 4
    batch = make_batch(4, 32, vocab=64)
    m = engine.train_batch(batch)
    assert np.isfinite(float(m["loss"]))
