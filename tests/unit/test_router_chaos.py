"""Router chaos soak (ISSUE 11): a 2-replica mixed continuous-batching
load under every router-level fault must complete every admitted request
with outputs BIT-IDENTICAL to a fault-free SINGLE-replica run.

The schedule exercises the three router fault kinds in one soak, plus a
saturation spill storm driven through the serving-level ``pool_exhaust``
seam:

  * ``heartbeat_loss``   — replica r0 goes silent for 4 rounds while alive:
    the breaker OPENs (``replica_degraded``), nothing migrates (fencing —
    no death evidence), and the half-open probe closes it again
    (``replica_recovered``) once heartbeats return;
  * ``pool_exhaust`` storm + arrival burst — both replicas' pools squeeze
    while arrivals keep coming: the first-choice replica's queue watermark
    sheds and the router SPILLS to the sibling (``request_spilled``)
    instead of surfacing ``AdmissionRejected``;
  * ``router_partition`` — r0 alive but unreachable for 3 rounds:
    consecutive dispatch faults OPEN the breaker, the injector tears the
    newest rendezvous generation manifest (the registry's generation reads
    survive via the ``current_generation`` torn-newest fallback), in-flight
    work stalls and continues after the heal (``replica_recovered``);
  * ``replica_kill``     — r1 SIGTERM-drains through the integrity chain
    mid-decode; the router detects the heartbeat loss, resumes the drained
    snapshot onto r0 (``request_migrated`` per request, cross-engine
    re-prefill determinism), and ``serve_lost_requests == 0``.

The disaggregated soak (ISSUE 19) drives a prefill + 2-decode fleet
through the ``kv_handoff`` seam (a corrupted payload caught by the crc, a
failed transfer) plus a decode-replica kill: every degraded handoff falls
back to re-prefill, failed-over work re-parks on the prefill tier and is
re-handed to the surviving decode replica, and the outputs stay
bit-identical to the fault-free single-replica run.

Slow tier: three engine builds + a 30+ round routed load. Runs under
tests/run_slow.sh with its own budget (ROUTER_CHAOS_BUDGET).
"""

import collections
import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.router import RouterConfig, ServingRouter
from deepspeed_tpu.inference.scheduler import AdmissionRejected
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.faults import FaultInjector, FaultSchedule

pytestmark = pytest.mark.slow

N_REQUESTS = 32


@pytest.fixture(autouse=True)
def _clean_robustness_state():
    rb_faults.clear()
    rb_events.clear()
    yield
    rb_faults.clear()
    rb_events.clear()


def _readable_json(path):
    try:
        with open(path) as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


def _model():
    return make_model(TransformerConfig(
        vocab_size=128, hidden_size=128, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=256, position_type="rotary",
        activation="silu_glu", norm_type="rmsnorm", tie_embeddings=False,
        dtype=jnp.float32, attention_impl="xla"))


def _load():
    rng = np.random.default_rng(11)
    return [(rng.integers(0, 128, size=(int(n),)).astype(np.int32), int(k))
            for n, k in zip(rng.integers(5, 40, N_REQUESTS),
                            rng.integers(8, 15, N_REQUESTS))]


def _serving(model, params, **kw):
    d = dict(max_seqs=3, block_size=16, max_model_len=128,
             decode_quantum=2, prompt_bucket=16, num_blocks=20,
             decode_backend="xla", max_queue=4)
    d.update(kw)
    return deepspeed_tpu.init_serving(model, config={}, serving=d,
                                      dtype=jnp.float32, params=params)


# arrival plan (router round -> submissions): steady ramp, a burst INTO
# the exhaustion storm (spill evidence), and a late tail so the kill at
# round 22 finds in-flight work on both replicas
FEED = {**{r: 2 for r in range(9)},          # rounds 0-8: 18
        10: 3, 11: 3,                        # storm burst: 6
        17: 2, 18: 2, 19: 2, 20: 2}          # late tail: 8


class TestRouterChaosSoak:
    def test_soak_bit_identical_to_single_replica(self, tmp_path):
        model = _model()
        params = jax.device_get(model.init(jax.random.PRNGKey(0)))
        reqs = _load()

        # ---- fault-free SINGLE-replica baseline -----------------------
        base = _serving(model, params, max_seqs=8, num_blocks=72,
                        max_queue=None).run(list(reqs))
        assert len(base) == N_REQUESTS

        # ---- routed chaos run -----------------------------------------
        rb_events.clear()
        jsonl = str(tmp_path / "tel" / "router_events.jsonl")
        t = [0.0]
        router = ServingRouter(RouterConfig(
            store_dir=str(tmp_path / "store"),
            drain_dir=str(tmp_path / "drains"),
            dead_after_s=2.5, breaker_faults=2, breaker_probe_after=2,
            clock=lambda: t[0], telemetry_jsonl=jsonl))
        # replicas carry NO jsonl sink: the router owns the one drain of
        # the process-wide event queue
        router.register("r0", _serving(model, params))
        router.register("r1", _serving(model, params))
        gen0 = router.generation()["generation"]
        inj = rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "heartbeat_loss", "at": 4, "replica": 0, "times": 4},
            # serving_round indices: 2 per router round while both live —
            # 20..23 squeezes BOTH replicas' pools over rounds 10-11
            {"kind": "pool_exhaust", "at": 20, "times": 4, "keep": 0},
            {"kind": "router_partition", "at": 16, "replica": 0,
             "times": 3},
            {"kind": "replica_kill", "at": 22, "replica": 1},
        ], seed=5)))

        pending = collections.deque(reqs)
        outs, rounds, retry_shed = {}, 0, 0
        torn_mid = fallback_mid = None
        while pending or not router.done:
            feed = FEED.get(rounds, 2 if rounds > 20 else 0)
            for _ in range(min(feed, len(pending))):
                p, k = pending[0]
                try:
                    router.add_request(p, k)
                except AdmissionRejected:
                    retry_shed += 1
                    break            # all saturated: retry next round
                pending.popleft()
            for r in router.step():
                outs[r.rid] = r.output
            if rounds == 17:
                # mid-partition: the injector tore the NEWEST generation
                # manifest; generation reads must fall back to the newest
                # readable one, not return None (which would let a later
                # publish erase the history with generation 0)
                store = router.config.store_dir
                gens = sorted(fn for fn in os.listdir(store)
                              if fn.startswith("gen_")
                              and ".tmp." not in fn)
                torn_mid = not _readable_json(
                    os.path.join(store, gens[-1]))
                fallback_mid = router.generation()
            t[0] += 1.0
            rounds += 1
            assert rounds < 2000, "soak did not converge"
        rb_faults.clear()

        # every scheduled fault actually fired
        fired = {f["kind"] for f in inj.fired}
        assert fired == {"heartbeat_loss", "pool_exhaust",
                         "router_partition", "replica_kill"}, fired

        # ---- the acceptance bar ---------------------------------------
        st = router.stats()
        assert rounds >= 30, rounds
        assert st["lost_requests"] == 0.0, st
        assert st["failovers"] == 1.0 and st["migrated"] >= 1.0, st
        assert st["spilled"] >= 1.0, st          # the storm spilled
        assert st["completed"] == float(N_REQUESTS), st

        # every admitted request completed, BIT-IDENTICAL to the
        # fault-free single-replica run
        assert set(outs) == set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged under router chaos")

        # breaker episodes: heartbeat loss AND partition each degraded
        # and recovered; the kill degraded terminally
        degraded = rb_events.history("replica_degraded")
        assert {e["reason"] for e in degraded} >= {"heartbeat_loss",
                                                   "dispatch_faults"}
        recovered = rb_events.history("replica_recovered")
        assert len(recovered) >= 2, recovered
        migrated = rb_events.history("request_migrated")
        assert migrated and all(e["src"] == "r1" and e["dst"] == "r0"
                                for e in migrated)
        # fencing: the heartbeat_loss episode migrated nothing (every
        # migration came from the kill's drain snapshot)
        assert all(e["origin"] == "drain" for e in migrated)

        # the partition tore the NEWEST generation manifest mid-run; the
        # registry's reads fell back to the previous readable one (never
        # None), the failover's later publish healed the torn filename
        # by replacing it, and the membership history stayed monotone
        assert torn_mid is True, "the partition never tore a manifest"
        assert fallback_mid is not None
        assert fallback_mid["generation"] == gen0
        cur = router.generation()
        assert cur["generation"] > gen0          # failover re-published
        assert cur["hosts"] == ["r0"]            # r1 left the membership

        # ---- events visible in the telemetry JSONL --------------------
        types = set()
        for p in glob.glob(os.path.join(os.path.dirname(jsonl), "*")):
            with open(p) as f:
                for line in f:
                    try:
                        types.add(json.loads(line).get("type"))
                    except ValueError:
                        pass
        assert {"fault_injected", "replica_degraded", "replica_recovered",
                "request_migrated", "replica_failover", "request_spilled",
                "serving_drained"} <= types, types


# arrival plan for the disaggregated soak: 2/round for 8 rounds — the
# late admissions are still decoding when the kill lands at round 12
N_DISAGG = 16
DISAGG_FEED = {r: 2 for r in range(8)}


class TestDisaggChaosSoak:
    def test_disagg_soak_kv_faults_and_decode_kill(self, tmp_path):
        """ISSUE 19: a prefill + 2-decode fleet under the ``kv_handoff``
        seam (one corrupted payload — caught by the receiver's crc — and
        one failed transfer) plus a SIGTERM kill of a decode replica
        mid-soak. The degraded handoffs fall back to re-prefill, the
        killed replica's work fails over and (if it lands on the prefill
        tier) is re-handed to the surviving decode replica, and every
        output stays BIT-IDENTICAL to the fault-free single-replica run.
        """
        model = _model()
        params = jax.device_get(model.init(jax.random.PRNGKey(0)))
        rng = np.random.default_rng(7)
        reqs = [(rng.integers(0, 128, size=(int(n),)).astype(np.int32),
                 int(k))
                for n, k in zip(rng.integers(5, 40, N_DISAGG),
                                rng.integers(10, 17, N_DISAGG))]

        # ---- fault-free SINGLE-replica baseline -----------------------
        base = _serving(model, params, max_seqs=8, num_blocks=72,
                        max_queue=None).run(list(reqs))
        assert len(base) == N_DISAGG

        # ---- disaggregated chaos run ----------------------------------
        rb_events.clear()
        t = [0.0]
        router = ServingRouter(RouterConfig(
            store_dir=str(tmp_path / "store"),
            drain_dir=str(tmp_path / "drains"),
            dead_after_s=2.5, clock=lambda: t[0]))
        router.register("pre0", _serving(model, params, role="prefill"),
                        role="prefill")
        router.register("dec0", _serving(model, params, role="decode"),
                        role="decode")
        router.register("dec1", _serving(model, params, role="decode"),
                        role="decode")
        inj = rb_faults.install(FaultInjector(FaultSchedule([
            # 0-based handoff-attempt indices: every request hands off
            # exactly once (plus re-handoffs after the kill), so 1 and 3
            # land inside the first wave
            {"kind": "kv_handoff", "at": 1, "mode": "corrupt"},
            {"kind": "kv_handoff", "at": 3},
            # registration order: pre0=0 dec0=1 dec1=2 — kill the second
            # decode replica while the late tail is still decoding on it
            {"kind": "replica_kill", "at": 12, "replica": 2},
        ], seed=5)))

        pending = collections.deque(reqs)
        outs, rounds = {}, 0
        while pending or not router.done:
            for _ in range(min(DISAGG_FEED.get(rounds, 0), len(pending))):
                p, k = pending[0]
                try:
                    router.add_request(p, k)
                except AdmissionRejected:
                    break            # saturated: retry next round
                pending.popleft()
            for r in router.step():
                outs[r.rid] = r.output
            t[0] += 1.0
            rounds += 1
            assert rounds < 2000, "disagg soak did not converge"
        rb_faults.clear()

        fired = {f["kind"] for f in inj.fired}
        assert fired == {"kv_handoff", "replica_kill"}, fired
        assert sum(f["kind"] == "kv_handoff" for f in inj.fired) == 2

        # ---- the acceptance bar ---------------------------------------
        st = router.stats()
        assert st["lost_requests"] == 0.0, st
        assert st["completed"] == float(N_DISAGG), st
        # every admitted request crossed the prefill->decode hop once;
        # failed-over work may re-hand after re-parking on pre0
        assert st["handoffs"] >= float(N_DISAGG), st
        assert st["handoff_fallbacks"] == 2.0, st
        assert st["failovers"] == 1.0 and st["migrated"] >= 1.0, st

        # the two degraded hops are visible as kv=False handoff events;
        # every other hop shipped KV bytes
        hops = rb_events.history("request_handoff")
        assert sum(not e["kv"] for e in hops) == 2, hops
        assert sum(bool(e["kv"]) for e in hops) >= N_DISAGG - 2, hops
        assert all(e["src"] in ("pre0",) for e in hops), hops

        # the kill's drain snapshot migrated off dec1, never onto the
        # dead replica
        migrated = rb_events.history("request_migrated")
        assert migrated and all(e["src"] == "dec1" and e["dst"] != "dec1"
                                for e in migrated), migrated

        # bit-identical to the fault-free single-replica run: the seam
        # and the kill degrade throughput, never correctness
        assert set(outs) == set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged under disagg chaos")
