"""Collective overlap & comm deferral (comm.schedule + analysis overlap).

Pins the ISSUE-4 tentpole contracts:
  * deferred gradient sync (comm.deferred_grad_sync) trains BIT-FOR-BIT
    identically to the per-microbatch path over 20 fp16 steps with a forced
    overflow at step 7 (mirroring test_dataloader_prefetch's parity idiom),
    across ZeRO stages 1/2/3 on a 2-dev mesh, including the fused K-step
    program and the hierarchical 2D-mesh reduction;
  * the stage-2 collective census is INDEPENDENT of
    gradient_accumulation_steps when deferral is on (exact pin), and the
    per-microbatch grad sync scales exactly gas x when it is off
    (microbatch-unrolled lowering makes each sync a distinct static site);
  * the hierarchical data=2 x fsdp=4 reduction census is pinned exactly;
  * the overlap analyzer classifies scheduled collectives as
    overlapped/exposed and gates on analysis.max_exposed_collectives;
  * the 1/gas scaling is folded into the scan accumulator update — no
    post-scan full-grad-tree division sweep (jaxpr op-count pin).

Bit-parity methodology: deferred sync REORDERS the gradient summation
(per-device partials sum across microbatches before crossing the wire), so
float parity is bitwise exactly when the sums themselves are exact. The
parity model uses integer-valued data with a loss whose per-step gradient
arithmetic stays exact (integer column sums scaled by powers of two), which
makes every step's reduced gradient bit-identical by construction — any bit
difference in the trained state is a real defect in the deferred path, not
rounding. A quadratic-loss first-step check covers the grad computation at
exact inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis import OverlapAudit, AnalysisSettings
from deepspeed_tpu.analysis.hlo_parse import overlap_summary, parse_overlap
from deepspeed_tpu.comm import schedule as comm_sched
from jax.sharding import PartitionSpec as P


# --------------------------------------------------------------------------
# parity models (exact-arithmetic by construction)
# --------------------------------------------------------------------------

class IntLinearMean:
    """loss = mean(x @ w): the gradient is an integer column-sum of x scaled
    by powers of two — exact under ANY summation order, so eager and
    deferred reductions must agree bit-for-bit every step."""

    name = "int-linear-mean"

    def __init__(self, d=8):
        self.d = d

    def init(self, rng):
        return {"w": ((jnp.arange(self.d * self.d) % 5 - 2)
                      .reshape(self.d, self.d).astype(jnp.float32)) * 0.5}

    @property
    def logical_axes(self):
        return {"w": None}

    def loss_fn(self, params, batch, rng, deterministic):
        y = batch["x"] @ params["w"].astype(batch["x"].dtype)
        return jnp.mean(y.astype(jnp.float32))


class IntLinearSq(IntLinearMean):
    """loss = mean((x @ w)^2): grads depend on w (exact only at integer
    params) — used for the first-step bitwise check of the deferred grad
    computation itself."""

    name = "int-linear-sq"

    def loss_fn(self, params, batch, rng, deterministic):
        y = batch["x"] @ params["w"].astype(batch["x"].dtype)
        return jnp.mean(jnp.square(y).astype(jnp.float32))


def fp16_cfg(stage, axes, deferred, gas=4, batch=16, **overrides):
    cfg = {"train_batch_size": batch,
           "gradient_accumulation_steps": gas,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "fp16": {"enabled": True, "initial_scale_power": 8},
           "bf16": {"enabled": False},
           "zero_optimization": {"stage": stage},
           "mesh": {"axes": axes},
           "comm": {"deferred_grad_sync": deferred},
           "steps_per_print": 100}
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k] = {**cfg[k], **v}
        else:
            cfg[k] = v
    return cfg


def int_batches(n=20, boost_at=7, rows=16, d=8):
    """Integer-valued batches; the boosted batch pushes the fp16-scaled grad
    products past f32 max (2 * 2^126 * 2 = 2^128 -> inf) for a forced
    overflow at `boost_at` on every path."""
    rng = np.random.default_rng(0)
    batches = [{"x": rng.integers(-2, 3, size=(rows, d)).astype(np.float32)}
               for _ in range(n)]
    boost = np.full((rows, d), 2.0, np.float32) * np.float32(2.0 ** 126)
    batches[boost_at] = {"x": boost}
    return batches


def w_bits(engine):
    w = np.asarray(jax.device_get(engine.state["params"]["w"]))
    return w.view(np.uint32)


def run_steps(engine, batches):
    for b in batches:
        engine.train_batch(b)
    return engine


# --------------------------------------------------------------------------
# deferred vs per-microbatch: bit-for-bit over 20 fp16 steps
# --------------------------------------------------------------------------

class TestDeferredParity:
    @pytest.mark.parametrize("stage,axes", [
        (1, {"data": 2}), (2, {"data": 2}), (3, {"fsdp": 2})])
    def test_bit_for_bit_20_steps_with_overflow(self, stage, axes, devices8):
        batches = int_batches()
        eager, *_ = deepspeed_tpu.initialize(
            model=IntLinearMean(), config=fp16_cfg(stage, axes, False),
            devices=devices8[:2])
        deferred, *_ = deepspeed_tpu.initialize(
            model=IntLinearMean(), config=fp16_cfg(stage, axes, True),
            devices=devices8[:2])
        run_steps(eager, batches)
        run_steps(deferred, batches)
        assert eager.global_steps == deferred.global_steps == 20
        assert eager.skipped_steps == deferred.skipped_steps == 1
        assert eager.get_loss_scale() == deferred.get_loss_scale()
        np.testing.assert_array_equal(w_bits(eager), w_bits(deferred))
        # the applied-update counter skipped exactly the overflow step
        applied = np.asarray(jax.device_get(deferred.state["step"]))
        assert int(applied.reshape(-1)[0]) == 19

    def test_fused_k_steps_deferred_bit_for_bit(self, devices8):
        """pipeline.fuse_steps=4 x deferred sync: 5 dispatches cover 20
        steps; the shard_map region threads through the unrolled program."""
        batches = int_batches()
        ref, *_ = deepspeed_tpu.initialize(
            model=IntLinearMean(), config=fp16_cfg(2, {"data": 2}, False),
            devices=devices8[:2])
        run_steps(ref, batches)
        fused, *_ = deepspeed_tpu.initialize(
            model=IntLinearMean(),
            config=fp16_cfg(2, {"data": 2}, True,
                            pipeline={"fuse_steps": 4, "in_flight": 2}),
            devices=devices8[:2])
        fused.train_batches(iter(batches), 20)
        assert fused.global_steps == 20
        assert fused.skipped_steps == ref.skipped_steps == 1
        np.testing.assert_array_equal(w_bits(ref), w_bits(fused))

    def test_hierarchical_2d_bit_for_bit(self, devices8):
        """data=2 x fsdp=4: deferred + hierarchical reduction (fsdp-phase
        reduce-scatter, data-phase all-reduce) trains bit-identically."""
        batches = int_batches(n=10, boost_at=3)
        axes = {"data": 2, "fsdp": 4}
        eager, *_ = deepspeed_tpu.initialize(
            model=IntLinearMean(), config=fp16_cfg(2, axes, False, gas=2),
            devices=devices8)
        hier, *_ = deepspeed_tpu.initialize(
            model=IntLinearMean(),
            config=fp16_cfg(2, axes, True, gas=2,
                            comm={"deferred_grad_sync": True,
                                  "hierarchical_grad_reduce": True}),
            devices=devices8)
        run_steps(eager, batches)
        run_steps(hier, batches)
        assert eager.skipped_steps == hier.skipped_steps == 1
        np.testing.assert_array_equal(w_bits(eager), w_bits(hier))

    def test_quadratic_first_step_bitwise(self, devices8):
        """Grad computation parity at exact (integer) params: the very first
        optimizer step of a quadratic loss must match bitwise — this pins
        the deferred path's normalization (1/gas, 1/data, loss scale)
        exactly; later steps reorder sums over irrational params and are
        rounding-, not correctness-, different."""
        batches = int_batches(n=1, boost_at=0)
        batches[0] = {"x": np.random.default_rng(1).integers(
            -2, 3, size=(16, 8)).astype(np.float32)}
        eager, *_ = deepspeed_tpu.initialize(
            model=IntLinearSq(), config=fp16_cfg(2, {"data": 2}, False),
            devices=devices8[:2])
        deferred, *_ = deepspeed_tpu.initialize(
            model=IntLinearSq(), config=fp16_cfg(2, {"data": 2}, True),
            devices=devices8[:2])
        me = eager.train_batch(batches[0])
        md = deferred.train_batch(batches[0])
        assert float(me["grad_norm"]) == float(md["grad_norm"])
        np.testing.assert_array_equal(w_bits(eager), w_bits(deferred))


# --------------------------------------------------------------------------
# census pins: gas-independence (deferred) vs exactly-gas-x (eager)
# --------------------------------------------------------------------------

def tiny_model():
    from deepspeed_tpu.models import TransformerConfig, make_model
    return make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=64, dtype=jnp.float32, attention_impl="xla"),
        name="lint-tiny")


BATCH16 = {"input_ids": np.zeros((16, 16), np.int32)}

# exact censuses for the tiny model / 16x16 batch / 2-device data mesh
# (measured; re-measure with engine.audit() if a deliberate change shifts
# them). DEFERRED is the same dict for EVERY gas; the eager per-microbatch
# grad sync adds exactly EAGER_AR_PER_MB all-reduces per extra microbatch.
STAGE2_DEFERRED_CENSUS = {"all-reduce": 21, "reduce-scatter": 20,
                          "all-gather": 20}
STAGE2_EAGER_GAS1_AR = 41       # = test_analysis.STAGE2_CENSUS["all-reduce"]
EAGER_AR_PER_MB = 21            # per-microbatch grad sync all-reduces


def census_of(stage, axes, devices, gas, *, deferred, unroll=0, hier=False,
              expect=None, fuse=0):
    cfg = {"train_batch_size": 16,
           "gradient_accumulation_steps": gas,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "bf16": {"enabled": False},
           "zero_optimization": {"stage": stage,
                                 "stage3_param_persistence_threshold": 0},
           "mesh": {"axes": axes},
           "comm": {"deferred_grad_sync": deferred,
                    "hierarchical_grad_reduce": hier,
                    "microbatch_unroll": unroll},
           "steps_per_print": 100}
    if fuse:
        cfg["pipeline"] = {"fuse_steps": fuse}
    if expect is not None:
        cfg["analysis"] = {"expect_collectives": expect}
    engine, *_ = deepspeed_tpu.initialize(model=tiny_model(), config=cfg,
                                          devices=devices)
    report = engine.audit(batch=BATCH16)
    return report


class TestDeferredCensus:
    def test_stage2_census_independent_of_gas(self, devices8):
        """The acceptance pin: with deferral on, the stage-2 collective
        census is IDENTICAL for gas=1 and gas=4 — one data-axis sync per
        step, period — and matches the exact pin (enforced through
        analysis.expect_collectives so the report gate itself fires)."""
        censuses = {}
        for gas in (1, 4):
            rep = census_of(2, {"data": 2}, devices8[:2], gas, deferred=True,
                            expect=STAGE2_DEFERRED_CENSUS)
            assert rep.ok, f"gas={gas}:\n{rep.summary()}"
            censuses[gas] = {k: c["count"]
                             for k, c in rep.census["train_step"].items()}
        assert censuses[1] == censuses[4] == STAGE2_DEFERRED_CENSUS, censuses

    def test_stage2_eager_grad_sync_scales_exactly_gas_x(self, devices8):
        """With deferral OFF and the microbatch loop unrolled (each sync a
        distinct static site), the per-microbatch grad all-reduce count is
        exactly linear in gas: ar(gas) = ar(1) + EAGER_AR_PER_MB*(gas-1)."""
        rep = census_of(2, {"data": 2}, devices8[:2], 4, deferred=False,
                        unroll=4)
        assert rep.ok, rep.summary()
        got = {k: c["count"] for k, c in rep.census["train_step"].items()}
        assert got["all-reduce"] == STAGE2_EAGER_GAS1_AR \
            + EAGER_AR_PER_MB * 3, got
        # no reduce-scatter sites vanish into the deferred shape by accident
        assert got["all-reduce"] > STAGE2_DEFERRED_CENSUS["all-reduce"]

    @pytest.mark.slow
    def test_stage2_eager_linearity_at_gas2(self, devices8):
        rep = census_of(2, {"data": 2}, devices8[:2], 2, deferred=False,
                        unroll=2)
        got = {k: c["count"] for k, c in rep.census["train_step"].items()}
        assert got["all-reduce"] == STAGE2_EAGER_GAS1_AR + EAGER_AR_PER_MB

    @pytest.mark.slow
    def test_fused_deferred_census_scales_by_k(self, devices8):
        """The fused K-step program threads the deferred shard_map region K
        times: its census must be exactly K x the deferred single-step pin
        (CollectiveAudit scales expect_collectives by meta fuse_steps).
        Slow tier: the K-step lowering was the quick tier's single most
        expensive compile (~13s on a 1-core box); the fuse_steps pin
        scaling it exercises is also covered (slow) by test_analysis's
        test_fused_program_census_scales_by_k."""
        rep = census_of(2, {"data": 2}, devices8[:2], 2, deferred=True,
                        expect=STAGE2_DEFERRED_CENSUS, fuse=2)
        assert rep.ok, rep.summary()
        single = {k: c["count"] for k, c in rep.census["train_step"].items()}
        fused = {k: c["count"]
                 for k, c in rep.census["train_step_fused"].items()}
        assert single == STAGE2_DEFERRED_CENSUS
        assert fused == {k: 2 * v
                         for k, v in STAGE2_DEFERRED_CENSUS.items()}, fused

    def test_hierarchical_2d_census_pinned(self, devices8):
        """Exact pin for the hierarchical data=2 x fsdp=4 reduction (the
        MULTICHIP mesh plan): the deferred boundary runs an fsdp-phase
        reduce-scatter and the data-axis phase operates on the sharded
        buffer. An unexplained shift here is a comm-schedule regression."""
        rep = census_of(3, {"data": 2, "fsdp": 4}, devices8, 1,
                        deferred=True, hier=True)
        assert rep.ok, rep.summary()
        got = {k: c["count"] for k, c in rep.census["train_step"].items()}
        want = {"all-reduce": 59, "all-gather": 61, "all-to-all": 7,
                "reduce-scatter": 20, "collective-permute": 11}
        assert got == want, got
        # the decomposition's signature: explicit reduce-scatter sites AND
        # data-axis all-reduces coexist
        assert got["reduce-scatter"] >= 20 and got["all-reduce"] > 0


# --------------------------------------------------------------------------
# overlap analyzer (scheduled-HLO classification)
# --------------------------------------------------------------------------

SCHED_HLO = """\
HloModule jit_step, is_scheduled=true

ENTRY %main (p0: f32[1024,1024]) -> f32[1024,1024] {
  %p0 = f32[1024,1024]{1,0} parameter(0)
  %ag = (f32[512,1024]{1,0}, f32[1024,1024]{1,0}) all-gather-start(f32[512,1024]{1,0} %x), channel_id=1
  %fused = f32[1024,1024]{1,0} fusion(f32[1024,1024]{1,0} %p0), kind=kLoop, calls=%fc
  %agd = f32[1024,1024]{1,0} all-gather-done((f32[512,1024]{1,0}, f32[1024,1024]{1,0}) %ag)
  %rs = (f32[1024,1024]{1,0}, f32[512,1024]{1,0}) reduce-scatter-start(f32[1024,1024]{1,0} %fused), channel_id=2
  %rsd = f32[512,1024]{1,0} reduce-scatter-done((f32[1024,1024]{1,0}, f32[512,1024]{1,0}) %rs)
  %ar = f32[1024,1024]{1,0} all-reduce(f32[1024,1024]{1,0} %agd), channel_id=3, to_apply=%add
  %tiny = f32[4]{0} all-reduce(f32[4]{0} %small), channel_id=4, to_apply=%add
  %pp = (f32[1024,1024]{1,0}, f32[1024,1024]{1,0}, u32[], u32[]) collective-permute-start(f32[1024,1024]{1,0} %agd), channel_id=5
  %w = (s32[], f32[1024,1024]{1,0}) while(s32[] %c, f32[1024,1024]{1,0} %agd), condition=%cond, body=%wbody
  %ppd = f32[1024,1024]{1,0} collective-permute-done((f32[1024,1024]{1,0}, f32[1024,1024]{1,0}, u32[], u32[]) %pp)
}
"""


class TestOverlapAnalyzer:
    def test_classification(self):
        ops = parse_overlap(SCHED_HLO)
        by = {}
        for op in ops:
            by.setdefault(op.kind, []).append(op)
        # async pair with a fusion scheduled between start/done: overlapped
        ag = by["all-gather"][0]
        assert ag.is_async and ag.overlapped and ag.gap_ops == 1
        assert ag.nbytes == 1024 * 1024 * 4  # max tuple element, not sum
        # async pair scheduled back-to-back: exposed
        rs = by["reduce-scatter"][0]
        assert rs.is_async and not rs.overlapped
        # synchronous collective: exposed by construction
        ar = by["all-reduce"][0]
        assert not ar.is_async and not ar.overlapped
        # a TUPLE-result compute op (while loops, multi-output fusions)
        # between start/done still counts as overlap
        pp = by["collective-permute"][0]
        assert pp.is_async and pp.overlapped and pp.gap_ops == 1

    def test_classification_without_name_sigils(self):
        """Some XLA dump styles print instruction names without the '%'
        sigil; start/done pairing must still resolve (boundary-anchored
        matching, no substring collisions)."""
        ops = parse_overlap(SCHED_HLO.replace("%", ""))
        by = {}
        for op in ops:
            by.setdefault(op.kind, []).append(op)
        assert by["all-gather"][0].overlapped
        assert not by["reduce-scatter"][0].overlapped
        assert by["collective-permute"][0].overlapped

    def test_summary_respects_min_bytes(self):
        summary = overlap_summary(parse_overlap(SCHED_HLO), min_bytes=1024)
        assert summary["overlapped"]["count"] == 2
        assert summary["exposed"]["count"] == 2  # tiny all-reduce exempt
        assert summary["exposed"]["bytes"] == (1024 * 1024 * 4) * 2

    def test_gate_fires_only_when_configured(self):
        from deepspeed_tpu.analysis.program import ProgramArtifacts
        art = ProgramArtifacts(name="p", optimized_hlo=SCHED_HLO)
        audit = OverlapAudit()
        assert audit.analyze(art, AnalysisSettings()) == []  # report-only
        findings = audit.analyze(
            art, AnalysisSettings(max_exposed_collectives=0,
                                  min_exposed_bytes=1024))
        rules = {f.rule for f in findings}
        assert rules == {"collective-exposed"}
        kinds = {f.ident for f in findings}
        assert kinds == {"all-reduce", "reduce-scatter"}
        # budget of 2 tolerates both exposed ops
        assert audit.analyze(
            art, AnalysisSettings(max_exposed_collectives=2,
                                  min_exposed_bytes=1024)) == []

    def test_engine_report_carries_overlap_census(self, devices8):
        rep = census_of(2, {"data": 2}, devices8[:2], 1, deferred=False)
        ov = rep.overlap["train_step"]
        total = ov["overlapped"]["count"] + ov["exposed"]["count"]
        assert total > 0  # every parsed collective is classified
        assert "overlap" in rep.to_dict()

    def test_static_join_prices_exposed_comm(self):
        from deepspeed_tpu.telemetry import joined_rates
        static = {"comm_bytes_per_step": 1000,
                  "exposed_comm_bytes_per_step": 250,
                  "overlapped_comm_bytes_per_step": 750,
                  "flops_per_step": 0}
        rates = joined_rates(static, steps_per_sec=2.0, peak_flops=1.0,
                             interconnect_bytes_per_sec=1e6)
        assert rates["exposed_comm_ms"] == pytest.approx(250 / 1e6 * 1e3)
        assert rates["overlap_efficiency"] == pytest.approx(0.75)
        # no interconnect estimate -> no modeled wire time, no crash
        rates = joined_rates(static, 2.0, 1.0)
        assert "exposed_comm_ms" not in rates


# --------------------------------------------------------------------------
# satellite: 1/gas folded into the scan accumulator update
# --------------------------------------------------------------------------

class TestGasFold:
    def test_no_post_scan_division_sweep(self):
        """The mean scaling rides the accumulator update inside the scan;
        the OUTER jaxpr must not contain one div per grad leaf after the
        scan (the single remaining div is the loss mean)."""
        from deepspeed_tpu.runtime.engine import Engine
        params = {"a": jnp.ones((8, 8)), "b": jnp.ones((4,)),
                  "c": jnp.ones((8, 4))}
        batch = {"x": jnp.ones((16, 8))}

        def micro(p, mb, r):
            loss = jnp.mean((mb["x"] @ p["a"] @ p["c"]) ** 2) \
                + jnp.sum(p["b"])
            return loss, jax.tree.map(lambda q: q * 0 + loss, p)

        jaxpr = jax.make_jaxpr(
            lambda p, b, r: Engine._accum_micro_grads(micro, p, b, 4, r))(
                params, batch, jax.random.PRNGKey(0))
        outer = [eqn.primitive.name for eqn in jaxpr.jaxpr.eqns]
        assert outer.count("div") == 1, outer  # loss mean only
        assert "scan" in outer

    def test_folded_mean_matches_reference(self):
        from deepspeed_tpu.runtime.engine import Engine
        params = {"w": jnp.arange(8.0)}
        batch = {"x": jnp.arange(32.0).reshape(32, 1)}

        def micro(p, mb, r):
            return jnp.sum(mb["x"]), jax.tree.map(
                lambda q: q + jnp.sum(mb["x"]), jax.tree.map(
                    jnp.zeros_like, p))

        grads, loss = Engine._accum_micro_grads(
            micro, params, batch, 4, jax.random.PRNGKey(0))
        # sum over microbatches / gas
        per_mb = [np.sum(np.arange(32.0).reshape(4, 8, 1)[i])
                  for i in range(4)]
        np.testing.assert_allclose(np.asarray(grads["w"]),
                                   np.mean(per_mb), rtol=1e-6)

    def test_unrolled_scan_matches_loop(self):
        """comm.microbatch_unroll >= gas fully unrolls; values match the
        scan path exactly (same op order per element)."""
        from deepspeed_tpu.runtime.engine import Engine
        params = {"w": jnp.ones((4,))}
        batch = {"x": jnp.arange(16.0).reshape(16, 1)}

        def micro(p, mb, r):
            s = jnp.sum(mb["x"])
            return s, {"w": p["w"] * s}

        g1, l1 = Engine._accum_micro_grads(micro, params, batch, 4,
                                           jax.random.PRNGKey(0))
        g2, l2 = Engine._accum_micro_grads(micro, params, batch, 4,
                                           jax.random.PRNGKey(0), unroll=4)
        np.testing.assert_array_equal(np.asarray(g1["w"]),
                                      np.asarray(g2["w"]))
        assert float(l1) == float(l2)


# --------------------------------------------------------------------------
# comm.schedule spec surgery
# --------------------------------------------------------------------------

class TestScheduleSpecs:
    def test_drop_axis(self):
        assert comm_sched.drop_axis(P("data", None), "data") == P()
        assert comm_sched.drop_axis(P(("data", "fsdp"), None), "data") \
            == P("fsdp")
        assert comm_sched.drop_axis(P(None, "data"), "data") == P()
        assert comm_sched.drop_axis(P("fsdp"), "data") == P("fsdp")

    def test_axis_dim(self):
        assert comm_sched.axis_dim(P(None, "data"), "data") == 1
        assert comm_sched.axis_dim(P(("data", "fsdp")), "fsdp") == 0
        assert comm_sched.axis_dim(P("fsdp"), "data") is None

    def test_hierarchical_spec(self):
        from deepspeed_tpu.parallel.mesh import MeshPlan
        plan = MeshPlan(data=2, fsdp=4)
        # already fsdp-sharded (stage 3): unchanged
        assert comm_sched.hierarchical_spec(P("fsdp", "data"), (8, 8), plan) \
            == P("fsdp", "data")
        # unsharded dim divisible by fsdp gains the intermediate
        assert comm_sched.hierarchical_spec(P("data", None), (8, 8), plan) \
            == P("data", "fsdp")
        # nothing divides -> unchanged (tiny tensors ride the flat path)
        assert comm_sched.hierarchical_spec(P(), (3,), plan) == P()

    def test_deferred_supported_gates(self):
        from deepspeed_tpu.parallel.mesh import MeshPlan
        ok, _ = comm_sched.deferred_supported(MeshPlan(data=2, fsdp=4))
        assert ok
        for plan in (MeshPlan(data=2, pipe=2), MeshPlan(data=2, seq=2),
                     MeshPlan(data=2, expert=2)):
            ok, why = comm_sched.deferred_supported(plan)
            assert not ok and why


# --------------------------------------------------------------------------
# satellite: AIOHandle.__del__ must not raise after a failed init
# --------------------------------------------------------------------------

class TestAIOHandleDel:
    def test_del_without_handle_attr(self):
        from deepspeed_tpu.ops.aio import AIOHandle
        h = AIOHandle.__new__(AIOHandle)  # __init__ "failed" before _h
        h.close()   # no AttributeError
        h.__del__()  # no noise at collection either
        assert h._h is None

    def test_close_idempotent_without_lib(self):
        from deepspeed_tpu.ops.aio import AIOHandle
        h = AIOHandle.__new__(AIOHandle)
        h._h = 123          # handle present but _lib missing (mid-init)
        h.close()
        assert h._h is None
        h.close()
