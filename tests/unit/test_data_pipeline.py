"""Data efficiency tests (reference: runtime/data_pipeline/
curriculum_scheduler.py, data_sampler.py, data_routing/basic_layer.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler, DistributedSampler, RandomLTDScheduler,
    apply_seqlen_curriculum, random_ltd_layer)
from tests.conftest import make_batch


class TestCurriculumScheduler:
    def test_fixed_linear(self):
        s = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 128,
                                 "schedule_type": "fixed_linear",
                                 "schedule_config": {
                                     "total_curriculum_step": 100,
                                     "difficulty_step": 8}})
        assert s.update_difficulty(0) == 8
        mid = s.update_difficulty(50)
        assert 60 <= mid <= 76 and mid % 8 == 0
        assert s.update_difficulty(100) == 128
        assert s.update_difficulty(10**6) == 128

    def test_fixed_root_grows_faster_early(self):
        lin = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 128,
                                   "schedule_type": "fixed_linear",
                                   "schedule_config": {
                                       "total_curriculum_step": 100}})
        root = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 128,
                                    "schedule_type": "fixed_root",
                                    "schedule_config": {
                                        "total_curriculum_step": 100,
                                        "root_degree": 2}})
        assert root.update_difficulty(25) > lin.update_difficulty(25)

    def test_fixed_discrete(self):
        s = CurriculumScheduler({"schedule_type": "fixed_discrete",
                                 "min_difficulty": 8, "max_difficulty": 64,
                                 "schedule_config": {
                                     "difficulty": [16, 32, 64],
                                     "max_step": [10, 20, 30]}})
        assert s.update_difficulty(5) == 16
        assert s.update_difficulty(15) == 32
        assert s.update_difficulty(99) == 64

    def test_truncation(self):
        b = {"input_ids": np.ones((4, 64), np.int32),
             "labels": np.ones((4, 64), np.int32)}
        out = apply_seqlen_curriculum(b, 16)
        assert out["input_ids"].shape == (4, 16)

    @pytest.mark.slow
    def test_engine_curriculum_seqlen(self, devices8):
        """Engine truncates batches per schedule; short early steps train."""
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False},
            "curriculum_learning": {
                "enabled": True, "curriculum_type": "seqlen",
                "min_difficulty": 16, "max_difficulty": 64,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 4,
                                    "difficulty_step": 16}},
            "steps_per_print": 1000})
        b = make_batch(8, 64, vocab=64)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(6)]
        assert np.isfinite(losses).all()
        assert engine._curriculum.get_current_difficulty() == 64


class TestDistributedSampler:
    def test_partition_and_coverage(self):
        idx = []
        for r in range(4):
            s = DistributedSampler(103, num_replicas=4, rank=r, shuffle=True,
                                   seed=7)
            part = list(s)
            assert len(part) == 103 // 4
            idx.extend(part)
        assert len(set(idx)) == len(idx)  # disjoint across ranks

    def test_epoch_reshuffles(self):
        s = DistributedSampler(64, num_replicas=2, rank=0, shuffle=True)
        a = list(s)
        s.set_epoch(1)
        b = list(s)
        assert a != b and sorted(a) != sorted(b) or set(a) != set(b)

    def test_no_drop_last_pads(self):
        total = []
        for r in range(4):
            s = DistributedSampler(10, num_replicas=4, rank=r, shuffle=False,
                                   drop_last=False)
            total.extend(list(s))
        assert len(total) == 12 and set(total) == set(range(10))

    def test_dataloader_integration(self):
        from deepspeed_tpu.runtime.dataloader import DataLoader
        data = [{"x": np.full((2,), i, np.int32)} for i in range(40)]
        s = DistributedSampler(40, num_replicas=2, rank=1, shuffle=False)
        dl = DataLoader(data, batch_size=5, sampler=s)
        batches = list(dl)
        assert len(batches) == 4
        seen = {int(v[0]) for b in batches for v in b["x"]}
        assert seen == set(range(20, 40))  # rank 1's contiguous shard


class TestRandomLTD:
    def test_layer_subset_passthrough(self):
        """Un-selected tokens pass through unchanged; selected ones get the
        layer applied with their true positions."""
        B, S, H, keep = 2, 16, 8, 8
        x = jnp.asarray(np.random.default_rng(0).normal(size=(B, S, H)),
                        jnp.float32)

        def layer_fn(xs, positions=None, mask=None):
            return xs + 1.0, jnp.float32(0.0)

        y, aux = random_ltd_layer(x, layer_fn, keep, jax.random.PRNGKey(0),
                                  positions=None, mask=None)
        delta = np.asarray(y - x)
        changed = (np.abs(delta) > 1e-6).any(axis=-1)
        assert changed.sum() == B * keep  # exactly keep tokens per row

    def test_keep_all_is_identity_path(self):
        x = jnp.ones((1, 8, 4))
        y = random_ltd_layer(x, lambda xs, **kw: xs * 2, 8,
                             jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(y), 2 * np.asarray(x))

    def test_scheduler_buckets(self):
        s = RandomLTDScheduler({"random_ltd": {
            "min_value": 64, "max_value": 512,
            "total_steps": 100, "seq_step": 64}})
        assert s.kept_tokens(0, 512) == 64
        assert s.kept_tokens(100, 512) == 512
        assert s.kept_tokens(50, 512) % 64 == 0
        assert s.kept_tokens(50, 128) == 128  # capped at seq

    @pytest.mark.slow
    def test_engine_random_ltd_trains(self, devices8):
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False},
            "data_efficiency": {
                "enabled": True,
                "data_routing": {"random_ltd": {
                    "enabled": True, "min_value": 16, "max_value": 64,
                    "total_steps": 4, "seq_step": 16}}},
            "steps_per_print": 1000})
        b = make_batch(8, 64, vocab=64)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(6)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        # schedule reached full seq -> model back to dense
        assert engine._ltd_keep == 64


class TestIndexedDatasetAnalyzer:
    """Reference: data_sampling/indexed_dataset.py + data_analyzer.py:18 +
    the curriculum sampler that consumes the analyzer's index."""

    def _write(self, tmp, n=50, seed=0):
        from deepspeed_tpu.runtime.data_pipeline import write_indexed_dataset
        rng = np.random.default_rng(seed)
        samples = [rng.integers(0, 100, size=rng.integers(4, 64))
                   for _ in range(n)]
        prefix = str(tmp / "ds")
        count = write_indexed_dataset(samples, prefix)
        return prefix, samples, count

    def test_indexed_roundtrip(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import IndexedDataset
        prefix, samples, count = self._write(tmp_path)
        ds = IndexedDataset(prefix)
        assert len(ds) == count == len(samples)
        for i in (0, 7, len(ds) - 1):
            np.testing.assert_array_equal(ds[i], samples[i].astype(np.int32))
        np.testing.assert_array_equal(ds.lengths,
                                      [len(s) for s in samples])

    def test_analyzer_and_curriculum_sampler(self, tmp_path):
        from deepspeed_tpu.runtime.data_pipeline import (
            CurriculumScheduler, CurriculumSampler, DataAnalyzer,
            IndexedDataset)
        prefix, samples, _ = self._write(tmp_path)
        ds = IndexedDataset(prefix)
        paths = DataAnalyzer().run(ds, str(tmp_path / "metrics"))
        vals = np.load(tmp_path / "metrics" / "seqlen_values.npy")
        np.testing.assert_array_equal(vals, [len(s) for s in samples])
        order = np.load(tmp_path / "metrics" / "seqlen_order.npy")
        assert (np.diff(vals[order]) >= 0).all()

        sched = CurriculumScheduler({
            "curriculum_type": "seqlen", "min_difficulty": 8,
            "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 10,
                                "difficulty_step": 1}})
        sampler = CurriculumSampler(str(tmp_path / "metrics"), "seqlen",
                                    sched, batch_size=4)
        early = sampler.sample(1)
        late = sampler.sample(10)
        assert len(early) == 4 and len(late) == 4
        # early in the curriculum: only short samples are eligible
        max_early = max(len(samples[i]) for i in early)
        assert max_early <= max(16, 8 + 4)  # near min_difficulty
        # sharded: two ranks see disjoint rows of the same draw
        s0 = CurriculumSampler(str(tmp_path / "metrics"), "seqlen", sched,
                               batch_size=2, rank=0, world_size=2, seed=3)
        s1 = CurriculumSampler(str(tmp_path / "metrics"), "seqlen", sched,
                               batch_size=2, rank=1, world_size=2, seed=3)
        a, b = s0.sample(5), s1.sample(5)
        assert len(a) == 2 and len(b) == 2
        # ranks partition ONE shared draw: identical RNG stream, strided
        # rows — a per-rank seed would duplicate/skip samples
        ref = CurriculumSampler(str(tmp_path / "metrics"), "seqlen", sched,
                                batch_size=2, rank=0, world_size=2, seed=3)
        pool = ref.eligible(5)
        full = ref._rng.choice(pool, size=4, replace=len(pool) < 4)
        np.testing.assert_array_equal(a, full[0::2])
        np.testing.assert_array_equal(b, full[1::2])
