"""TiledLinear / memory-efficient linear (reference: zero/tiling.py:29,
zero/linear.py:42; test model: tests/unit/runtime/zero/test_zero_tiled.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.tiled_linear import (memory_efficient_linear,
                                            split_tiled_weight, tiled_linear)


def _data(In=48, Out=36, B=4, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, In), jnp.float32),
            jax.random.normal(ks[1], (In, Out), jnp.float32),
            jax.random.normal(ks[2], (Out,), jnp.float32))


def test_memory_efficient_matches_dense():
    x, w, b = _data()

    def loss_me(x, w, b):
        return jnp.sum(memory_efficient_linear(x, w, b) ** 2)

    def loss_ref(x, w, b):
        return jnp.sum((x @ w + b) ** 2)

    v1, g1 = jax.value_and_grad(loss_me, argnums=(0, 1, 2))(x, w, b)
    v2, g2 = jax.value_and_grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(float(v1[0] if isinstance(v1, tuple) else v1),
                               float(v2[0] if isinstance(v2, tuple) else v2),
                               rtol=1e-6)
    for a, c in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-5,
                                   atol=1e-6)


@pytest.mark.parametrize("out_tiles,in_tiles", [(1, 1), (3, 1), (1, 4),
                                                (3, 4), (5, 7)])
def test_tiled_matches_dense(out_tiles, in_tiles):
    x, w, b = _data(In=49, Out=37)  # non-divisible on purpose

    def loss_t(x, w, b):
        return jnp.sum(tiled_linear(x, w, b, out_tiles=out_tiles,
                                    in_tiles=in_tiles) ** 2)

    def loss_ref(x, w, b):
        return jnp.sum((x @ w + b) ** 2)

    v1, g1 = jax.value_and_grad(loss_t, argnums=(0, 1, 2))(x, w, b)
    v2, g2 = jax.value_and_grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
    for a, c in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=1e-4,
                                   atol=1e-5)


def test_split_tiled_weight_roundtrip():
    _, w, _ = _data(In=16, Out=23)
    tiles = split_tiled_weight(w, 5)
    assert len(tiles) == 5
    np.testing.assert_array_equal(np.asarray(jnp.concatenate(tiles, axis=1)),
                                  np.asarray(w))


def test_sharded_tiled_linear(devices8):
    """Under an fsdp mesh the per-tile matmuls gather one fsdp-sharded tile
    at a time (the ZeRO-3 TiledLinear behavior)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(devices8).reshape(8, 1), ("fsdp", "tensor"))
    x, w, b = _data(In=64, Out=32)
    ws = jax.device_put(w, NamedSharding(mesh, P("fsdp", None)))
    with mesh:
        y = jax.jit(lambda x, w: tiled_linear(x, w, out_tiles=4))(x, ws)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w), rtol=1e-5,
                               atol=1e-6)
