"""Paged Pallas decode-attention parity (reference test model:
tests/unit/ops kernel-vs-torch parity, SURVEY §4).

The XLA reference is the materialized block-table gather fed through
``models/transformer._decode_attention`` (the ring-buffer math with a
per-slot cursor) — the same function the serving engine's XLA backend uses,
so the masking contract lives in ONE place instead of a re-implemented
reference drifting here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.decode_attention import paged_decode_attention


def _ref_paged(q, k_pool, v_pool, tables, lens, k_row, v_row):
    from deepspeed_tpu.models.transformer import _decode_attention
    S = q.shape[0]
    NB, Nkv, bs, D = k_pool.shape
    MB = tables.shape[1]

    def view(pool):
        g = jnp.take(pool, tables, axis=0)        # [S, MB, Nkv, bs, D]
        return g.transpose(0, 2, 1, 3, 4).reshape(S, Nkv, MB * bs, D)

    return _decode_attention(q, view(k_pool), view(v_pool),
                             jnp.asarray(lens, jnp.int32), None,
                             kv_row=(k_row, v_row))


def _rand_case(key, S, NB, MB, Nkv, rep, bs, D, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(key), 5)
    q = jax.random.normal(ks[0], (S, 1, Nkv * rep, D), dtype)
    k_pool = jax.random.normal(ks[1], (NB, Nkv, bs, D), dtype)
    v_pool = jax.random.normal(ks[2], (NB, Nkv, bs, D), dtype)
    k_row = jax.random.normal(ks[3], (S, Nkv, 1, D), dtype)
    v_row = jax.random.normal(ks[4], (S, Nkv, 1, D), dtype)
    # distinct non-trash blocks per slot (block 0 reserved), shuffled so the
    # table gather is a REAL permutation, not identity
    rng = np.random.default_rng(key)
    ids = rng.permutation(np.arange(1, NB))[:S * MB].reshape(S, MB)
    return q, k_pool, v_pool, jnp.asarray(ids, jnp.int32), k_row, v_row


@pytest.mark.parametrize("lens", [[0, 1], [5, 37], [32, 64], [64, 63]])
@pytest.mark.parametrize("rep", [1, 4])
def test_paged_parity(lens, rep):
    """Mixed per-slot lengths: empty slot, partial block, exact block
    boundary, full table."""
    S, NB, MB, Nkv, bs, D = 2, 8, 2, 2, 32, 64
    q, kp, vp, tables, kr, vr = _rand_case(sum(lens) * 7 + rep, S, NB, MB,
                                           Nkv, rep, bs, D)
    lens = jnp.asarray(lens, jnp.int32)
    out = paged_decode_attention(q, kp, vp, tables, lens, kv_row=(kr, vr))
    ref = _ref_paged(q, kp, vp, tables, lens, kr, vr)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_paged_bf16():
    S, NB, MB, Nkv, rep, bs, D = 2, 10, 3, 4, 2, 32, 64
    q, kp, vp, tables, kr, vr = _rand_case(11, S, NB, MB, Nkv, rep, bs, D,
                                           jnp.bfloat16)
    lens = jnp.asarray([70, 96], jnp.int32)
    out = paged_decode_attention(q, kp, vp, tables, lens, kv_row=(kr, vr))
    ref = _ref_paged(q, kp, vp, tables, lens, kr, vr)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_trash_block_and_stale_rows_ignored():
    """Block 0 (the reserved trash block null table entries point at) and
    rows past each slot's length hold huge garbage — none of it may leak
    into the output (the scheduler reuses freed blocks without zeroing)."""
    S, NB, MB, Nkv, rep, bs, D = 2, 6, 2, 2, 1, 32, 64
    q, kp, vp, tables, kr, vr = _rand_case(3, S, NB, MB, Nkv, rep, bs, D)
    kp = kp.at[0].set(1e4)                    # trash block
    vp = vp.at[0].set(1e4)
    lens = jnp.asarray([40, 0], jnp.int32)
    # slot 0's second block is half stale; slot 1 is EMPTY with an all-null
    # table -> its output must be exactly the fresh-row value
    tables = tables.at[1].set(0)
    blk2 = int(tables[0, 1])
    kp = kp.at[blk2, :, 8:].set(1e4)          # rows 40.. of slot 0 stale
    vp = vp.at[blk2, :, 8:].set(1e4)
    out = paged_decode_attention(q, kp, vp, tables, lens, kv_row=(kr, vr))
    ref = _ref_paged(q, kp, vp, tables, lens, kr, vr)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert float(jnp.max(jnp.abs(out))) < 100.0
    # the empty slot attends only to itself
    np.testing.assert_allclose(np.asarray(out[1]),
                               np.asarray(vr[1].reshape(1, Nkv * rep, D)),
                               rtol=1e-5, atol=1e-5)


def test_table_permutation_invariance():
    """Physically scattered blocks must read identically to the same data
    laid out contiguously — the whole point of the table indirection."""
    S, NB, MB, Nkv, rep, bs, D = 1, 9, 4, 2, 2, 32, 64
    q, kp, vp, _, kr, vr = _rand_case(5, S, NB, MB, Nkv, rep, bs, D)
    lens = jnp.asarray([100], jnp.int32)
    t1 = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    t2 = jnp.asarray([[5, 7, 6, 8]], jnp.int32)
    # copy the logical contents of layout 1 into layout 2's blocks
    kp2, vp2 = kp, vp
    for a, b in zip([1, 2, 3, 4], [5, 7, 6, 8]):
        kp2 = kp2.at[b].set(kp[a])
        vp2 = vp2.at[b].set(vp[a])
    o1 = paged_decode_attention(q, kp, vp, t1, lens, kv_row=(kr, vr))
    o2 = paged_decode_attention(q, kp2, vp2, t2, lens, kv_row=(kr, vr))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))


class TestInt8KVCache:
    """int8 KV storage (contiguous ring buffers AND the paged pool share
    this math): the per-position scales factor out of the d-contraction so
    both attention einsums run on int8 bytes (int8 MXU path on TPU) —
    dequant is fused into the read, nothing materializes. Parity vs the
    float-cache XLA decode attention."""

    def test_decode_attention_int8_parity(self):
        from deepspeed_tpu.models.transformer import (_decode_attention,
                                                      _quant_kv)
        B, Nkv, rep, T, D = 2, 4, 2, 128, 64
        ks = jax.random.split(jax.random.PRNGKey(3), 5)
        q = jax.random.normal(ks[0], (B, 1, Nkv * rep, D), jnp.float32)
        ck = jax.random.normal(ks[1], (B, Nkv, T, D), jnp.float32)
        cv = jax.random.normal(ks[2], (B, Nkv, T, D), jnp.float32)
        k_row = jax.random.normal(ks[3], (B, Nkv, 1, D), jnp.float32)
        v_row = jax.random.normal(ks[4], (B, Nkv, 1, D), jnp.float32)
        index = jnp.int32(100)
        ref = _decode_attention(q, ck, cv, index, kv_row=(k_row, v_row))
        kq, ksc = _quant_kv(ck)
        vq, vsc = _quant_kv(cv)
        got = _decode_attention(q, kq, vq, index, kv_row=(k_row, v_row),
                                kv_scale=(ksc, vsc))
        rel = (np.linalg.norm(np.asarray(got - ref).ravel())
               / np.linalg.norm(np.asarray(ref).ravel()))
        assert rel < 2e-2, rel

    @pytest.mark.slow
    def test_generate_int8_vs_float_first_logits(self):
        """Engine-level: prefill logits are exact (cache unused); the first
        decode step's logits (read through the quantized cache) stay close
        to the float-cache path."""
        import deepspeed_tpu
        from deepspeed_tpu.models import TransformerConfig, make_model

        cfg = TransformerConfig(vocab_size=128, hidden_size=64,
                                num_layers=2, num_heads=4, max_seq_len=256,
                                dtype=jnp.float32, attention_impl="xla")
        ids = np.random.default_rng(0).integers(0, 128, (2, 40),
                                                dtype=np.int32)
        outs = {}
        for kvb in (0, 8):
            model = make_model(cfg, name="tiny")
            eng = deepspeed_tpu.init_inference(
                model, config={"kv_cache_bits": kvb}, dtype=jnp.float32)
            assert eng.model.config.kv_cache_bits == kvb
            outs[kvb] = np.asarray(jax.device_get(
                eng.generate(ids, max_new_tokens=8)))
        # prompt region identical by construction; the check is on the
        # GENERATED region: greedy argmax through a ~1% attention
        # perturbation on this fixed seed keeps the first tokens equal
        assert (outs[0][:, :40] == outs[8][:, :40]).all()
        gen0, gen8 = outs[0][:, 40:], outs[8][:, 40:]
        assert (gen0[:, :4] == gen8[:, :4]).all(), (gen0, gen8)
