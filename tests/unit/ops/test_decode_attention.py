"""Length-aware Pallas decode attention parity (reference test model:
tests/unit/ops kernel-vs-torch parity, SURVEY §4)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.decode_attention import decode_attention


def _ref(q, ck, cv, index):
    B, _, Nq, D = q.shape
    Nkv, T = ck.shape[1], ck.shape[2]
    rep = Nq // Nkv
    qg = q.reshape(B, Nkv, rep, D)
    s = jnp.einsum("bgrd,bgtd->bgrt", qg.astype(jnp.float32),
                   ck.astype(jnp.float32)) / math.sqrt(D)
    s = jnp.where((jnp.arange(T) <= index)[None, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bgrt,bgtd->bgrd", p, cv.astype(jnp.float32))
    return out.reshape(B, 1, Nq, D).astype(q.dtype)


@pytest.mark.parametrize("index", [0, 5, 63, 130, 255])
@pytest.mark.parametrize("rep", [1, 4])
def test_decode_parity(index, rep):
    B, Nkv, T, D = 2, 2, 256, 64
    ks = jax.random.split(jax.random.PRNGKey(index + rep), 3)
    q = jax.random.normal(ks[0], (B, 1, Nkv * rep, D), jnp.float32)
    ck = jax.random.normal(ks[1], (B, Nkv, T, D), jnp.float32)
    cv = jax.random.normal(ks[2], (B, Nkv, T, D), jnp.float32)
    out = decode_attention(q, ck, cv, index, block_k=64)
    ref = _ref(q, ck, cv, index)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_decode_bf16():
    B, Nkv, rep, T, D = 1, 4, 2, 512, 64
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, 1, Nkv * rep, D), jnp.bfloat16)
    ck = jax.random.normal(ks[1], (B, Nkv, T, D), jnp.bfloat16)
    cv = jax.random.normal(ks[2], (B, Nkv, T, D), jnp.bfloat16)
    out = decode_attention(q, ck, cv, 100, block_k=128)
    ref = _ref(q.astype(jnp.float32), ck.astype(jnp.float32),
               cv.astype(jnp.float32), 100)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_garbage_beyond_index_ignored():
    """Rows past the cursor must not leak into the output even when they
    hold huge values (the uninitialized-ring-buffer case)."""
    B, Nkv, T, D = 1, 2, 128, 64
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, 1, 2, D), jnp.float32)
    ck = jax.random.normal(ks[1], (B, Nkv, T, D), jnp.float32)
    cv = jax.random.normal(ks[2], (B, Nkv, T, D), jnp.float32)
    ck = ck.at[:, :, 40:].set(1e4)
    cv = cv.at[:, :, 40:].set(1e4)
    out = decode_attention(q, ck, cv, 39, block_k=32)
    ref = _ref(q, ck, cv, 39)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert float(jnp.max(jnp.abs(out))) < 100.0


def _ref_row(q, ck, cv, index, k_row, v_row):
    """XLA reference for the fresh-row mode: buffer rows < index valid, the
    row's logit joins separately (mirrors models/transformer._decode_attention
    kv_row path)."""
    B, _, Nq, D = q.shape
    Nkv, T = ck.shape[1], ck.shape[2]
    rep = Nq // Nkv
    qg = q.reshape(B, Nkv, rep, D).astype(jnp.float32)
    s = jnp.einsum("bgrd,bgtd->bgrt", qg,
                   ck.astype(jnp.float32)) / math.sqrt(D)
    s = jnp.where((jnp.arange(T) < index)[None, None, None, :], s, -1e30)
    s1 = jnp.einsum("bgrd,bgtd->bgrt", qg,
                    k_row.astype(jnp.float32)) / math.sqrt(D)
    full = jnp.concatenate([s, s1], axis=-1)
    p = jax.nn.softmax(full, axis=-1)
    out = (jnp.einsum("bgrt,bgtd->bgrd", p[..., :T], cv.astype(jnp.float32))
           + p[..., T:] * v_row.astype(jnp.float32))
    return out.reshape(B, 1, Nq, D).astype(q.dtype)


@pytest.mark.parametrize("index", [0, 1, 63, 130, 255])
@pytest.mark.parametrize("rep", [1, 4])
def test_decode_row_mode_parity(index, rep):
    """kv_row mode: fresh row out of the buffer, strict prefix masking."""
    B, Nkv, T, D = 2, 2, 256, 64
    ks = jax.random.split(jax.random.PRNGKey(index * 7 + rep), 5)
    q = jax.random.normal(ks[0], (B, 1, Nkv * rep, D), jnp.float32)
    ck = jax.random.normal(ks[1], (B, Nkv, T, D), jnp.float32)
    cv = jax.random.normal(ks[2], (B, Nkv, T, D), jnp.float32)
    k_row = jax.random.normal(ks[3], (B, Nkv, 1, D), jnp.float32)
    v_row = jax.random.normal(ks[4], (B, Nkv, 1, D), jnp.float32)
    # garbage at >= index must not leak (ring rows incl. index are stale)
    ck = ck.at[:, :, index:].set(1e4)
    cv = cv.at[:, :, index:].set(1e4)
    out = decode_attention(q, ck, cv, index, kv_row=(k_row, v_row),
                           block_k=64)
    ref = _ref_row(q, ck, cv, index, k_row, v_row)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    assert float(jnp.max(jnp.abs(out))) < 100.0


class TestInt8KVCache:
    """int8 KV ring buffers (models/transformer kv_cache_bits=8): the
    per-position scales factor out of the d-contraction so both attention
    einsums run on int8 bytes (int8 MXU path on TPU). Parity vs the
    float-cache XLA decode attention."""

    def test_decode_attention_int8_parity(self):
        from deepspeed_tpu.models.transformer import (_decode_attention,
                                                      _quant_kv)
        B, Nkv, rep, T, D = 2, 4, 2, 128, 64
        ks = jax.random.split(jax.random.PRNGKey(3), 5)
        q = jax.random.normal(ks[0], (B, 1, Nkv * rep, D), jnp.float32)
        ck = jax.random.normal(ks[1], (B, Nkv, T, D), jnp.float32)
        cv = jax.random.normal(ks[2], (B, Nkv, T, D), jnp.float32)
        k_row = jax.random.normal(ks[3], (B, Nkv, 1, D), jnp.float32)
        v_row = jax.random.normal(ks[4], (B, Nkv, 1, D), jnp.float32)
        index = jnp.int32(100)
        ref = _decode_attention(q, ck, cv, index, kv_row=(k_row, v_row))
        kq, ksc = _quant_kv(ck)
        vq, vsc = _quant_kv(cv)
        got = _decode_attention(q, kq, vq, index, kv_row=(k_row, v_row),
                                kv_scale=(ksc, vsc))
        rel = (np.linalg.norm(np.asarray(got - ref).ravel())
               / np.linalg.norm(np.asarray(ref).ravel()))
        assert rel < 2e-2, rel

    @pytest.mark.slow
    def test_generate_int8_vs_float_first_logits(self):
        """Engine-level: prefill logits are exact (cache unused); the first
        decode step's logits (read through the quantized cache) stay close
        to the float-cache path."""
        import deepspeed_tpu
        from deepspeed_tpu.models import TransformerConfig, make_model

        cfg = TransformerConfig(vocab_size=128, hidden_size=64,
                                num_layers=2, num_heads=4, max_seq_len=256,
                                dtype=jnp.float32, attention_impl="xla")
        ids = np.random.default_rng(0).integers(0, 128, (2, 40),
                                                dtype=np.int32)
        outs = {}
        for kvb in (0, 8):
            model = make_model(cfg, name="tiny")
            eng = deepspeed_tpu.init_inference(
                model, config={"kv_cache_bits": kvb}, dtype=jnp.float32)
            assert eng.model.config.kv_cache_bits == kvb
            outs[kvb] = np.asarray(jax.device_get(
                eng.generate(ids, max_new_tokens=8)))
        # prompt region identical by construction; the check is on the
        # GENERATED region: greedy argmax through a ~1% attention
        # perturbation on this fixed seed keeps the first tokens equal
        assert (outs[0][:, :40] == outs[8][:, :40]).all()
        gen0, gen8 = outs[0][:, 40:], outs[8][:, 40:]
        assert (gen0[:, :4] == gen8[:, :4]).all(), (gen0, gen8)
