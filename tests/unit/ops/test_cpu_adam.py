"""Native host CPU-Adam parity (reference test model:
tests/unit/ops/adam/test_cpu_adam.py — kernel vs torch.optim.AdamW)."""

import numpy as np
import pytest

from deepspeed_tpu.ops.cpu_adam import CPUAdam, cpu_adam_available

pytestmark = pytest.mark.skipif(not cpu_adam_available(),
                                reason="native cpu_adam build unavailable")


def _ref_adamw(master, m, v, g, lr, b1, b2, eps, wd, step, awm=True):
    g = g.astype(np.float64)
    p = master.astype(np.float64)
    if wd and not awm:
        g = g + wd * p
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    c1, c2 = 1 - b1 ** step, 1 - b2 ** step
    upd = (m / c1) / (np.sqrt(v / c2) + eps)
    if wd and awm:
        upd = upd + wd * p
    return p - lr * upd, m, v


@pytest.mark.parametrize("n", [1000, 65537])
def test_f32_parity(n):
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=n).astype(np.float32)
    opt = CPUAdam(n, lr=1e-2, weight_decay=0.01)
    opt.load_master(p0)
    m = v = np.zeros(n, np.float64)
    master = p0.copy()
    for step in (1, 2, 3):
        g = rng.normal(size=n).astype(np.float32)
        out = opt.step(g, step)
        master, m, v = _ref_adamw(master, m, v, g, 1e-2, 0.9, 0.999, 1e-8,
                                  0.01, step)
        np.testing.assert_allclose(out, master, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(opt.master, master, rtol=2e-5, atol=2e-6)


def test_bf16_wire_parity():
    import ml_dtypes
    rng = np.random.default_rng(1)
    n = 4096
    p0 = rng.normal(size=n).astype(np.float32)
    opt = CPUAdam(n, lr=1e-2)
    opt.load_master(p0)
    g32 = rng.normal(size=n).astype(np.float32)
    gbits = g32.astype(ml_dtypes.bfloat16).view(np.uint16)
    out = opt.step(gbits, 1)
    assert out.dtype == np.uint16
    got = out.view(ml_dtypes.bfloat16).astype(np.float64)
    ref, _, _ = _ref_adamw(p0, np.zeros(n), np.zeros(n),
                           g32.astype(ml_dtypes.bfloat16).astype(np.float32),
                           1e-2, 0.9, 0.999, 1e-8, 0.0, 1)
    # bf16 wire both ways: ~3 decimal digits
    np.testing.assert_allclose(got, ref, rtol=1e-2, atol=1e-2)
    # the MASTER keeps full precision regardless of the wire dtype
    np.testing.assert_allclose(opt.master, ref, rtol=1e-5, atol=1e-5)


def test_grad_scale_and_norm():
    rng = np.random.default_rng(2)
    n = 1 << 14
    g = rng.normal(size=n).astype(np.float32)
    opt = CPUAdam(n, lr=1e-3)
    sq = opt.sq_norm(g)
    np.testing.assert_allclose(sq, float(np.sum(g.astype(np.float64) ** 2)),
                               rtol=1e-6)
    # grad_scale folds 1/loss_scale + clip into one multiplier
    opt.load_master(np.zeros(n, np.float32))
    out1 = opt.step(g * 4.0, 1, grad_scale=0.25).copy()
    opt2 = CPUAdam(n, lr=1e-3)
    opt2.load_master(np.zeros(n, np.float32))
    out2 = opt2.step(g, 1)
    np.testing.assert_allclose(out1, out2, rtol=1e-6, atol=1e-7)


def test_adagrad_sq_norm_guard(monkeypatch):
    """CPUAdagrad.sq_norm borrows the Adam lib's norm kernels; if the adam
    .so build failed while the adagrad .so built, it must raise the same
    RuntimeError as the step path — not AttributeError on None."""
    from deepspeed_tpu.ops import cpu_adam as _ca
    from deepspeed_tpu.ops.cpu_adagrad import CPUAdagrad
    monkeypatch.setattr(_ca, "_load", lambda: None)
    with pytest.raises(RuntimeError, match="cpu_adam library unavailable"):
        CPUAdagrad.sq_norm(None, np.ones(8, np.float32))
