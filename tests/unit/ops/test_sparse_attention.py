"""Block-sparse attention tests (reference:
ops/sparse_attention/sparse_self_attention.py + sparsity_config.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, VariableSparsityConfig, get_sparsity_config,
    reference_sparse_attention, sparse_attention)


def _qkv(B=2, S=64, N=2, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, N, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, N, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, N, D), jnp.float32)
    return q, k, v


class TestLayouts:
    def test_fixed_layout_shape_and_density(self):
        cfg = FixedSparsityConfig(block=16, num_local_blocks=2,
                                  num_global_blocks=1)
        L = cfg.make_layout(128)
        assert L.shape == (8, 8)
        assert L.sum() < 64            # actually sparse
        assert L[:, 0].all()           # global column
        assert all(L[i, (i // 2) * 2] for i in range(8))  # local window

    def test_bigbird_has_window_global_random(self):
        cfg = BigBirdSparsityConfig(block=16, num_random_blocks=1,
                                    num_sliding_window_blocks=3,
                                    num_global_blocks=1)
        L = cfg.make_layout(128)
        assert all(L[i, i] for i in range(8))   # diagonal (window)
        assert L[:, 0].all() and L[0, :].all()  # global

    def test_longformer_globals(self):
        cfg = BSLongformerSparsityConfig(block=16,
                                         num_sliding_window_blocks=1,
                                         global_block_indices=(2,))
        L = cfg.make_layout(128)
        assert L[:, 2].all() and L[2, :].all()

    def test_mode_registry(self):
        assert isinstance(get_sparsity_config("dense"), DenseSparsityConfig)
        assert isinstance(get_sparsity_config("variable"),
                          VariableSparsityConfig)
        with pytest.raises(ValueError):
            get_sparsity_config("nope")


LAYOUTS = [
    DenseSparsityConfig(block=16),
    FixedSparsityConfig(block=16, num_local_blocks=2, num_global_blocks=1),
    BigBirdSparsityConfig(block=16, num_random_blocks=1,
                          num_sliding_window_blocks=3, num_global_blocks=1),
    BSLongformerSparsityConfig(block=16, num_sliding_window_blocks=3,
                               global_block_indices=(0,)),
]


class TestKernelParity:
    @pytest.mark.parametrize("cfg", LAYOUTS, ids=lambda c: type(c).__name__)
    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_masked_reference(self, cfg, causal):
        q, k, v = _qkv()
        out = sparse_attention(q, k, v, cfg, causal=causal)
        ref = reference_sparse_attention(q, k, v, cfg, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gradients_match_reference(self):
        cfg = FixedSparsityConfig(block=16, num_local_blocks=2,
                                  num_global_blocks=1)
        q, k, v = _qkv(B=1, S=64, N=1, D=16, seed=3)

        def f_sparse(q, k, v):
            return jnp.sum(sparse_attention(q, k, v, cfg, causal=True) ** 2)

        def f_ref(q, k, v):
            return jnp.sum(
                reference_sparse_attention(q, k, v, cfg, causal=True) ** 2)

        gs = jax.grad(f_sparse, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gs, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-4,
                                       err_msg=f"d{name} mismatch")

    def test_empty_rows_are_zero(self):
        """A non-causal layout row with no active blocks yields zeros (the
        l==0 guard), not NaNs."""
        cfg = BSLongformerSparsityConfig(block=16,
                                         num_sliding_window_blocks=1,
                                         global_block_indices=())
        # causal row 0 block attends only to itself; make a row empty by
        # removing window: window=1 keeps the diagonal, so instead check
        # numerics stay finite on the sparsest layout
        q, k, v = _qkv(B=1, S=32, N=1, D=16)
        out = np.asarray(sparse_attention(q, k, v, cfg, causal=True))
        assert np.isfinite(out).all()

    def test_indivisible_seq_raises(self):
        q, k, v = _qkv(S=60)
        with pytest.raises(ValueError, match="divisible"):
            sparse_attention(q, k, v, FixedSparsityConfig(block=16))


@pytest.mark.slow
def test_transformer_with_sparse_attention_trains(devices8):
    """End-to-end: a model configured for bigbird sparse attention trains
    through the engine (the reference wires SparseSelfAttention the same
    way via its transformer integration)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, make_model
    from tests.conftest import make_batch
    model = make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=64, dtype=jnp.float32, attention_impl="xla",
        sparse_attention={"mode": "bigbird", "block": 16,
                          "num_random_blocks": 1,
                          "num_sliding_window_blocks": 3,
                          "num_global_blocks": 1}))
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False}, "steps_per_print": 1000})
    b = make_batch(8, 64, vocab=64)
    losses = [float(engine.train_batch(b)["loss"]) for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
