"""Flash attention parity vs XLA reference (reference test model:
tests/unit/ops kernel-vs-torch numerical parity, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.flash_attention import flash_attention, reference_attention


def _qkv(B=2, S=256, N=2, D=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, N, D)
    q = jax.random.normal(ks[0], shape, dtype)
    k = jax.random.normal(ks[1], shape, dtype)
    v = jax.random.normal(ks[2], shape, dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_parity(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_forward_uneven_blocks():
    # S=256 with block 128 -> 2 q blocks; also S smaller than default block
    q, k, v = _qkv(S=128)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_parity(causal):
    q, k, v = _qkv(B=1, S=256, N=2, D=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3, err_msg=f"d{name}")


def test_bf16_forward():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = reference_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=5e-2, atol=5e-2)


def test_model_integration_pallas_flag():
    """attention_impl='pallas' on CPU uses interpret mode end-to-end."""
    from deepspeed_tpu.models import TransformerConfig, make_model
    cfg = TransformerConfig(vocab_size=128, hidden_size=128, num_layers=1,
                            num_heads=2, head_dim=64, max_seq_len=128,
                            dtype=jnp.float32, attention_impl="pallas")
    cfg_ref = TransformerConfig(vocab_size=128, hidden_size=128, num_layers=1,
                                num_heads=2, head_dim=64, max_seq_len=128,
                                dtype=jnp.float32, attention_impl="xla")
    m, mr = make_model(cfg), make_model(cfg_ref)
    params = m.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 128)), jnp.int32)
    np.testing.assert_allclose(np.asarray(m.apply(params, ids)),
                               np.asarray(mr.apply(params, ids)),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_gqa_native_parity(causal):
    """Nkv < Nq: the kernel runs per KV head over the whole query group —
    outputs and grads must match the repeat-KV reference."""
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    B, S, Nq, Nkv, D = 2, 128, 8, 2, 32
    q = jax.random.normal(ks[0], (B, S, Nq, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Nkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Nkv, D), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    def loss_fa(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=32, block_k=32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    ga = jax.grad(loss_fa, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(ga, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"d{name}")


def test_gqa_indivisible_heads_raises():
    q = jnp.zeros((1, 64, 6, 16))
    k = jnp.zeros((1, 64, 4, 16))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, q)


@pytest.mark.parametrize("causal", [True, False])
def test_kv_padding_mask_parity(causal):
    """Padding masks are applied inside the kernel — parity with the masked
    XLA reference, forward AND grads (masked batches must not fall back to
    the O(S^2) path)."""
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    B, S, N, D = 2, 128, 2, 32
    q = jax.random.normal(ks[0], (B, S, N, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, N, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, N, D), jnp.float32)
    mask = np.ones((B, S), np.int32)
    mask[0, 100:] = 0
    mask[1, 64:] = 0
    maskj = jnp.asarray(mask)

    def ref(q, k, v):
        s = jnp.einsum("bsnd,btnd->bnst", q, k) / np.sqrt(D)
        if causal:
            cm = jnp.tril(jnp.ones((S, S), jnp.bool_))
            s = jnp.where(cm[None, None], s, -1e30)
        s = jnp.where(maskj[:, None, None, :] > 0, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bnst,btnd->bsnd", p, v)

    out = flash_attention(q, k, v, causal=causal, kv_mask=maskj,
                          block_q=32, block_k=32)
    expect = ref(q, k, v)
    valid = mask.astype(bool)
    np.testing.assert_allclose(np.asarray(out)[valid],
                               np.asarray(expect)[valid],
                               rtol=2e-4, atol=2e-4)

    g = jax.grad(lambda q: jnp.sum(
        (flash_attention(q, k, v, causal=causal, kv_mask=maskj,
                         block_q=32, block_k=32)
         * jnp.asarray(valid)[..., None, None]) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(
        (ref(q, k, v) * jnp.asarray(valid)[..., None, None]) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=5e-4, atol=5e-4)


def test_all_masked_row_outputs_zero():
    """A batch row whose kv_mask is entirely zero must produce zero outputs
    (not the mean of masked V: with m == s == NEG_INF, exp(0) == 1 — the
    M_FLOOR clamp keeps p at 0 so the l == 0 guard actually fires) and must
    not leak gradient into its K/V."""
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    B, S, N, D = 2, 128, 2, 32
    q = jax.random.normal(ks[0], (B, S, N, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, N, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, N, D), jnp.float32)
    mask = np.ones((B, S), np.int32)
    mask[1, :] = 0  # batch row 1 is all padding
    out = flash_attention(q, k, v, causal=False, kv_mask=jnp.asarray(mask),
                          block_q=32, block_k=32)
    np.testing.assert_array_equal(np.asarray(out)[1], 0.0)

    gk, gv = jax.grad(
        lambda k, v: jnp.sum(flash_attention(
            q, k, v, causal=False, kv_mask=jnp.asarray(mask),
            block_q=32, block_k=32) ** 2), argnums=(0, 1))(k, v)
    np.testing.assert_array_equal(np.asarray(gk)[1], 0.0)
    np.testing.assert_array_equal(np.asarray(gv)[1], 0.0)
    assert np.isfinite(np.asarray(gk)).all() and np.isfinite(np.asarray(gv)).all()


def test_nonpow2_block_request():
    """A non-power-of-two block_k must not degenerate to bk=1 — _pick_blocks
    rounds to a power of two first."""
    from deepspeed_tpu.ops.flash_attention import _pick_blocks
    bq, bk = _pick_blocks(1024, 384, 384)
    assert bk == 256 and bq == 256
    q, k, v = _qkv(S=256)
    out = flash_attention(q, k, v, causal=True, block_q=96, block_k=96)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
