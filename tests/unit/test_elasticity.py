"""Elasticity tests (reference: elasticity/elasticity.py + the reference's
tests/unit/elasticity/test_elastic.py cases)."""

import json

import numpy as np
import jax.numpy as jnp
import pytest

import deepspeed_tpu
from deepspeed_tpu.elasticity import (
    ElasticityError, compute_elastic_config, get_compatible_gpus)
from deepspeed_tpu.models import TransformerConfig, make_model
from tests.conftest import make_batch

# quick tier: `pytest -m 'not slow'` skips this module (rescale-resume paths rebuild engines)
pytestmark = pytest.mark.slow


def test_compatible_gpus():
    gpus = get_compatible_gpus(96, [2, 4], min_gpus=1, max_gpus=50)
    assert 48 in gpus and 24 in gpus and 8 in gpus
    assert 5 not in gpus  # 96 % (5*2) and % (5*4) both nonzero


def test_compute_config_basic():
    fb, valid, micro = compute_elastic_config(
        {"enabled": True, "max_train_batch_size": 2000,
         "micro_batch_sizes": [2, 4, 6], "min_gpus": 1, "max_gpus": 64},
        world_size=8)
    assert fb <= 2000 and fb % 8 == 0
    assert 8 in valid
    assert micro in (2, 4, 6) and (fb // 8) % micro == 0


def test_incompatible_world_size_raises():
    with pytest.raises(ElasticityError, match="not compatible"):
        compute_elastic_config(
            {"enabled": True, "max_train_batch_size": 8,
             "micro_batch_sizes": [8], "min_gpus": 1, "max_gpus": 64},
            world_size=3)


def test_disabled_raises():
    with pytest.raises(ElasticityError):
        compute_elastic_config({"enabled": False})


def test_engine_elastic_batch(devices8):
    """initialize() with elasticity picks batch/micro/gas for 8 devices."""
    import jax.numpy as jnp
    model = make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=32, dtype=jnp.float32, attention_impl="xla"))
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "elasticity": {"enabled": True, "max_train_batch_size": 64,
                       "micro_batch_sizes": [2, 4], "min_gpus": 1,
                       "max_gpus": 16},
        "steps_per_print": 1000})
    B = engine.config.train_batch_size
    assert B <= 64 and B % 8 == 0
    b = make_batch(B, 32, vocab=64)
    loss = float(engine.train_batch(b)["loss"])
    assert np.isfinite(loss)


def test_engine_elastic_conflicting_batch_raises(devices8):
    import jax.numpy as jnp
    model = make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=32, dtype=jnp.float32, attention_impl="xla"))
    with pytest.raises(ValueError, match="elasticity"):
        deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 16,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "elasticity": {"enabled": True}})


class TestElasticAgent:
    """Reference: elasticity/elastic_agent.py:25 — resume across scale
    events. Simulated in-process: the device world shrinks 8 -> 4 and the
    agent rebuilds + resumes from the latest checkpoint with the new
    micro/gas split."""

    def _factory(self):
        from deepspeed_tpu.models import TransformerConfig, make_model
        return lambda: make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))

    def _cfg(self):
        return {
            "optimizer": {"type": "adamw", "params": {"lr": 5e-3}},
            "bf16": {"enabled": False},
            "elasticity": {"enabled": True, "max_train_batch_size": 64,
                           "micro_batch_sizes": [2, 4],
                           "min_gpus": 1, "max_gpus": 8, "version": 0.2},
            "steps_per_print": 1000}

    def test_world_shrink_resumes(self, tmp_path):
        from deepspeed_tpu.elasticity import DSElasticAgent
        world = {"n": 8}
        agent = DSElasticAgent(self._factory(), self._cfg(), str(tmp_path),
                               checkpoint_interval=2,
                               device_count_fn=lambda: world["n"])
        assert agent.world == 8
        batch8 = agent.batch_size
        rng = np.random.default_rng(0)
        fixed = rng.integers(0, 64, (batch8, 32), dtype=np.int32)

        def make_batch_fn(bs):
            assert bs == batch8  # same global batch at every world size
            return {"input_ids": fixed}

        losses = [float(agent.train_batch(make_batch_fn)["loss"])
                  for _ in range(6)]
        step_before = agent.engine.global_steps

        world["n"] = 4  # scale event: half the devices disappear
        l_after = float(agent.train_batch(make_batch_fn)["loss"])
        assert agent.scale_events == 1 and agent.world == 4
        # resumed from the step-4 checkpoint, not from scratch
        assert agent.engine.global_steps == step_before + 1
        cfg = agent.engine.config
        assert cfg.train_batch_size == batch8  # same global batch
        assert (cfg.train_micro_batch_size_per_gpu
                * cfg.gradient_accumulation_steps * 4 == batch8)
        # loss continues from the trained trajectory (not re-initialized:
        # a fresh model starts near ln(64) ~ 4.16)
        assert l_after < losses[0] - 0.2, (l_after, losses)
        assert abs(l_after - losses[-1]) < 0.5  # continues, no reset jump

    def test_requires_elastic_section(self, tmp_path):
        from deepspeed_tpu.elasticity import DSElasticAgent
        with pytest.raises(ValueError, match="elasticity"):
            DSElasticAgent(self._factory(), {"train_batch_size": 8},
                           str(tmp_path))


class TestFailureRecovery:
    """Device-health watch + failed-step recovery (VERDICT r3 weakness #7:
    the only exercised trigger was a hand-injected world shrink; reference:
    torchelastic restarts on worker failure, elastic_agent.py:25)."""

    def _agent(self, tmp_path, **kw):
        from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
        from deepspeed_tpu.models import TransformerConfig, make_model

        def factory():
            return make_model(TransformerConfig(
                vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                max_seq_len=32, dtype=jnp.float32, attention_impl="xla"))

        cfg = {"optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "bf16": {"enabled": False}, "steps_per_print": 1000,
               "elasticity": {"enabled": True, "max_train_batch_size": 64,
                              "micro_batch_sizes": [2, 4],
                              "min_gpus": 1, "max_gpus": 8,
                              "version": 0.2}}
        return DSElasticAgent(factory, cfg, str(tmp_path), **kw)

    def test_probe_culls_dead_devices(self, tmp_path, devices8):
        from deepspeed_tpu.elasticity.elastic_agent import probe_devices
        assert probe_devices(devices8) == list(devices8)

        # fault injection: health_fn reports 3 devices dead
        healthy = {"n": 8}
        agent = self._agent(tmp_path,
                            health_fn=lambda: devices8[:healthy["n"]],
                            probe_interval=2, checkpoint_interval=1)
        assert agent.world == 8

        def batch(bs):
            rng = np.random.default_rng(0)
            return {"input_ids": rng.integers(0, 64, (bs, 32),
                                              dtype=np.int32)}

        l0 = float(agent.train_batch(batch)["loss"])
        agent.train_batch(batch)
        healthy["n"] = 4                       # 4 chips die
        agent.train_batch(batch)               # probe due -> rescale
        agent.train_batch(batch)
        assert agent.world == 4
        assert agent.scale_events == 1
        l1 = float(agent.train_batch(batch)["loss"])
        assert np.isfinite(l1) and l1 < l0

    def test_failed_step_rebuilds_and_retries(self, tmp_path, devices8):
        healthy = {"n": 8}
        agent = self._agent(tmp_path,
                            health_fn=lambda: devices8[:healthy["n"]],
                            checkpoint_interval=1)

        def batch(bs):
            rng = np.random.default_rng(1)
            return {"input_ids": rng.integers(0, 64, (bs, 32),
                                              dtype=np.int32)}

        agent.train_batch(batch)               # step 1 + checkpoint
        step_before = agent.engine.global_steps

        # inject a one-shot chip fault: the step raises AND the probe
        # afterwards finds a dead chip (a software error with all chips
        # healthy re-raises instead — tested below)
        real = agent.engine.train_batch
        state = {"fired": False}

        def faulty(b):
            if not state["fired"]:
                state["fired"] = True
                healthy["n"] = 4
                raise RuntimeError("TPU worker process crashed (injected)")
            return real(b)

        agent.engine.train_batch = faulty
        m = agent.train_batch(batch)           # fails once, recovers at 4
        assert agent.failure_events == 1
        assert agent.scale_events == 1         # fault-driven shrink counted
        assert agent.world == 4
        assert np.isfinite(float(m["loss"]))
        # the rebuilt engine resumed from the step-1 checkpoint
        assert agent.engine.global_steps == step_before + 1

    def test_rebuild_survives_corrupt_latest(self, tmp_path, devices8):
        """Satellite pin: the agent's rebuild path must survive a corrupt
        `latest` — the integrity chain walks the load back to the previous
        good tag instead of bricking the recovery with a deserialization
        error."""
        import json
        import os
        from deepspeed_tpu.robustness import events as rb_events
        from deepspeed_tpu.robustness import integrity
        rb_events.clear()
        healthy = {"n": 8}
        agent = self._agent(tmp_path,
                            health_fn=lambda: devices8[:healthy["n"]],
                            probe_interval=2, checkpoint_interval=1)

        def batch(bs):
            rng = np.random.default_rng(3)
            return {"input_ids": rng.integers(0, 64, (bs, 32),
                                              dtype=np.int32)}

        agent.train_batch(batch)      # step 1 + checkpoint (good tag)
        agent.train_batch(batch)      # step 2 + checkpoint (will corrupt)
        tag2 = os.path.join(str(tmp_path), "global_step2")
        with open(os.path.join(tag2, integrity.MANIFEST_FILE)) as f:
            files = json.load(f)["files"]
        victim = max(files.items(), key=lambda kv: kv[1]["size"])[0]
        with open(os.path.join(tag2, victim), "r+b") as f:
            f.truncate(os.path.getsize(os.path.join(tag2, victim)) // 2)

        healthy["n"] = 4              # probe-due step culls the world
        m = agent.train_batch(batch)  # rebuild: latest=step2 is corrupt
        assert agent.world == 4 and agent.scale_events == 1
        # resumed from step 1 (the newest VALID tag), then stepped once
        assert agent.engine.global_steps == 2
        assert np.isfinite(float(m["loss"]))
        falls = [e for e in rb_events.history("ckpt_fallback")
                 if e["resolved"] == "global_step1"]
        assert falls and falls[-1]["requested"] == "global_step2"

    def test_software_error_with_healthy_devices_reraises(self, tmp_path,
                                                          devices8):
        agent = self._agent(tmp_path, health_fn=lambda: devices8,
                            checkpoint_interval=1)

        def batch(bs):
            rng = np.random.default_rng(2)
            return {"input_ids": rng.integers(0, 64, (bs, 32),
                                              dtype=np.int32)}

        agent.train_batch(batch)

        def buggy(b):
            raise ValueError("bad batch (injected)")

        agent.engine.train_batch = buggy
        with pytest.raises(ValueError, match="bad batch"):
            agent.train_batch(batch)
        assert agent.failure_events == 0       # not recorded as a chip fault


def test_elastic_cli(tmp_path, capsys):
    """dstpu_elastic (reference: bin/ds_elastic over compute_elastic_config)."""
    import json
    from deepspeed_tpu.elasticity.elasticity import cli_main
    cfg = tmp_path / "ds.json"
    cfg.write_text(json.dumps({"elasticity": {
        "enabled": True, "max_train_batch_size": 64,
        "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 8,
        "version": 0.2}}))
    rc = cli_main([str(cfg), "-w", "4"])
    out = capsys.readouterr().out
    assert rc == 0 and "final train_batch_size" in out
    assert "micro batch at world=4" in out
    cfg2 = tmp_path / "bad.json"
    cfg2.write_text(json.dumps({"elasticity": {"enabled": False}}))
    assert cli_main([str(cfg2)]) == 1


class TestRendezvous:
    """Host-death rendezvous (reference: torchelastic store under
    elastic_agent.py:25): heartbeats detect a dead HOST (the per-chip
    probe can't), the leader publishes the next generation, survivors
    re-form at the smaller world."""

    def _rdzv(self, tmp, host, t):
        from deepspeed_tpu.elasticity import FileRendezvous
        return FileRendezvous(str(tmp), host, dead_after_s=10.0,
                              clock=lambda: t[0])

    def test_membership_and_leader(self, tmp_path):
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        b = self._rdzv(tmp_path, "host-b", t)
        a.heartbeat(); b.heartbeat()
        assert a.live_hosts() == ["host-a", "host-b"]
        assert a.is_leader() and not b.is_leader()

    def test_host_death_triggers_new_generation(self, tmp_path):
        from deepspeed_tpu.elasticity import reform_step
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        b = self._rdzv(tmp_path, "host-b", t)
        c = self._rdzv(tmp_path, "host-c", t)
        for r in (a, b, c):
            r.heartbeat()
        gen0 = a.propose_generation()
        assert gen0["generation"] == 0 and len(gen0["hosts"]) == 3
        # host-b dies: stops heartbeating; time passes beyond dead_after
        t[0] = 115.0
        a.heartbeat(); c.heartbeat()
        assert a.live_hosts() == ["host-a", "host-c"]
        assert a.should_reform()
        m = reform_step(a)
        assert m is not None and m["generation"] == 1
        assert m["hosts"] == ["host-a", "host-c"]
        assert m["coordinator"].startswith("host-a:")
        # the follower's round picks up the same manifest
        got = reform_step(c)
        assert got is not None and got["generation"] == 1

    def test_leader_death_elects_next(self, tmp_path):
        from deepspeed_tpu.elasticity import reform_step
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        b = self._rdzv(tmp_path, "host-b", t)
        a.heartbeat(); b.heartbeat()
        a.propose_generation()
        # the LEADER dies: host-b must take over and publish gen 1 with
        # itself as the coordinator
        t[0] = 115.0
        b.heartbeat()
        assert b.is_leader()
        m = reform_step(b)
        assert m["hosts"] == ["host-b"]
        assert m["coordinator"].startswith("host-b:")

    def test_rejoin_scales_back_up(self, tmp_path):
        from deepspeed_tpu.elasticity import reform_step
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        b = self._rdzv(tmp_path, "host-b", t)
        a.heartbeat(); b.heartbeat()
        a.propose_generation()
        t[0] = 115.0                      # b drops out
        reform_step(a)
        t[0] = 116.0                      # b comes back
        b.heartbeat()
        m = reform_step(a)
        assert m["generation"] == 2 and m["hosts"] == ["host-a", "host-b"]

    def test_stable_membership_is_noop(self, tmp_path):
        from deepspeed_tpu.elasticity import reform_step
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        a.heartbeat()
        a.propose_generation()
        assert reform_step(a) is None

    def test_graceful_leave(self, tmp_path):
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        b = self._rdzv(tmp_path, "host-b", t)
        a.heartbeat(); b.heartbeat()
        b.leave()
        assert a.live_hosts() == ["host-a"]

    def test_atomic_write_temps_are_invisible(self, tmp_path):
        """hb_*.json.tmp.<pid> / gen_*.json.tmp.<pid> share the scanned
        prefixes: a complete-but-unrenamed heartbeat temp must not
        double-count a host, and a torn gen temp (which sorts AFTER the
        real manifest) must not hide the published generation."""
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        b = self._rdzv(tmp_path, "host-b", t)
        a.heartbeat(); b.heartbeat()
        # a stalled writer left a COMPLETE heartbeat temp behind
        (tmp_path / "hb_host-b.json.tmp.4242").write_text(
            json.dumps({"host": "host-b", "beats": 9, "ts": 100.0}))
        assert a.live_hosts() == ["host-a", "host-b"]  # not duplicated
        m = a.propose_generation()
        assert m["hosts"] == ["host-a", "host-b"]
        # a torn manifest temp sorts last; current_generation must skip it
        (tmp_path / "gen_00000000.json.tmp.4242").write_text("{\"trunc")
        assert a.current_generation()["generation"] == 0
        assert not a.should_reform()  # no spurious reform either

    def test_wait_generation_keeps_heartbeating(self, tmp_path):
        """A follower blocked in wait_generation must not be declared dead
        mid-reform: the poll loop heartbeats, and the sleep comes from the
        injectable clock (a real sleep under a fake clock hangs)."""
        from deepspeed_tpu.elasticity import FileRendezvous
        t = [100.0]
        a = FileRendezvous(str(tmp_path), "host-a", dead_after_s=3.0,
                           clock=lambda: t[0])
        slept = []

        def fake_sleep(s):
            slept.append(s)
            t[0] += s
            if t[0] >= 108.0:   # leader publishes well past dead_after
                a.heartbeat()
                a.propose_generation()

        b = FileRendezvous(str(tmp_path), "host-b", dead_after_s=3.0,
                           clock=lambda: t[0], sleep=fake_sleep)
        a.heartbeat(); b.heartbeat()
        m = b.wait_generation(min_generation=0, timeout_s=60.0, poll_s=1.0)
        # the wait spanned >> dead_after_s, yet host-b stayed live because
        # the poll loop heartbeats — so it's IN the new generation
        assert t[0] - 100.0 > b.dead_after
        assert m["hosts"] == ["host-a", "host-b"]
        assert slept and all(s == 1.0 for s in slept)

    def test_elastic_batch_plan_for_new_world(self, tmp_path):
        """The reform manifest feeds compute_elastic_config: the new world
        gets a valid batch triad (the torchelastic-restart contract)."""
        from deepspeed_tpu.elasticity import (FileRendezvous,
                                              compute_elastic_config)
        t = [100.0]
        a = self._rdzv(tmp_path, "host-a", t)
        for h in ("host-a", "host-b", "host-c", "host-d"):
            FileRendezvous(str(tmp_path), h, dead_after_s=10.0,
                           clock=lambda: t[0]).heartbeat()
        m = a.propose_generation()
        chips_per_host = 4
        world = len(m["hosts"]) * chips_per_host
        fb, valid, micro = compute_elastic_config(
            {"enabled": True, "max_train_batch_size": 128,
             "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 64},
            world_size=world)
        assert fb % (micro * world) == 0
