"""Elasticity tests (reference: elasticity/elasticity.py + the reference's
tests/unit/elasticity/test_elastic.py cases)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.elasticity import (
    ElasticityError, compute_elastic_config, get_compatible_gpus)
from deepspeed_tpu.models import TransformerConfig, make_model
from tests.conftest import make_batch


def test_compatible_gpus():
    gpus = get_compatible_gpus(96, [2, 4], min_gpus=1, max_gpus=50)
    assert 48 in gpus and 24 in gpus and 8 in gpus
    assert 5 not in gpus  # 96 % (5*2) and % (5*4) both nonzero


def test_compute_config_basic():
    fb, valid, micro = compute_elastic_config(
        {"enabled": True, "max_train_batch_size": 2000,
         "micro_batch_sizes": [2, 4, 6], "min_gpus": 1, "max_gpus": 64},
        world_size=8)
    assert fb <= 2000 and fb % 8 == 0
    assert 8 in valid
    assert micro in (2, 4, 6) and (fb // 8) % micro == 0


def test_incompatible_world_size_raises():
    with pytest.raises(ElasticityError, match="not compatible"):
        compute_elastic_config(
            {"enabled": True, "max_train_batch_size": 8,
             "micro_batch_sizes": [8], "min_gpus": 1, "max_gpus": 64},
            world_size=3)


def test_disabled_raises():
    with pytest.raises(ElasticityError):
        compute_elastic_config({"enabled": False})


def test_engine_elastic_batch(devices8):
    """initialize() with elasticity picks batch/micro/gas for 8 devices."""
    import jax.numpy as jnp
    model = make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=32, dtype=jnp.float32, attention_impl="xla"))
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "elasticity": {"enabled": True, "max_train_batch_size": 64,
                       "micro_batch_sizes": [2, 4], "min_gpus": 1,
                       "max_gpus": 16},
        "steps_per_print": 1000})
    B = engine.config.train_batch_size
    assert B <= 64 and B % 8 == 0
    b = make_batch(B, 32, vocab=64)
    loss = float(engine.train_batch(b)["loss"])
    assert np.isfinite(loss)


def test_engine_elastic_conflicting_batch_raises(devices8):
    import jax.numpy as jnp
    model = make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=32, dtype=jnp.float32, attention_impl="xla"))
    with pytest.raises(ValueError, match="elasticity"):
        deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 16,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "elasticity": {"enabled": True}})
