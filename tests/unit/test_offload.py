"""ZeRO-Offload (host optimizer state) + native AIO tests (reference:
tests/unit/ops/aio/test_aio.py round-trips; offload covered in zero tests)."""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from tests.conftest import make_batch

# quick tier: `pytest -m 'not slow'` skips this module (swapper round trips rebuild engines)
pytestmark = pytest.mark.slow


def tiny_model():
    return make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))


class TestOptimizerOffload:
    def test_offload_matches_baseline(self):
        cfg = {"train_batch_size": 16,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "bf16": {"enabled": False}, "steps_per_print": 1000}
        e1, *_ = deepspeed_tpu.initialize(model=tiny_model(), config=cfg)
        cfg2 = dict(cfg)
        cfg2["zero_optimization"] = {"stage": 1,
                                     "offload_optimizer": {"device": "cpu"}}
        e2, *_ = deepspeed_tpu.initialize(model=tiny_model(), config=cfg2)
        batch = make_batch(16, 32, vocab=64)
        l1 = [float(e1.train_batch(batch)["loss"]) for _ in range(5)]
        l2 = [float(e2.train_batch(batch)["loss"]) for _ in range(5)]
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-5)
        # device=cpu routes through the chunk-streamed swapper: no fp32
        # optimizer state in device memory ("pinned" tier on TPU, plain
        # host buffers in the CPU test harness)
        assert e2.state["opt"] is None
        assert e2._swapper is not None
        assert e2._swapper.storage in ("pinned", "host")

    def test_offload_checkpoint_roundtrip(self, tmp_path):
        cfg = {"train_batch_size": 16,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "bf16": {"enabled": False}, "steps_per_print": 1000,
               "zero_optimization": {"stage": 1,
                                     "offload_optimizer": {"device": "cpu"}}}
        engine, *_ = deepspeed_tpu.initialize(model=tiny_model(), config=cfg)
        batch = make_batch(16, 32, vocab=64)
        for _ in range(3):
            engine.train_batch(batch)
        engine.save_checkpoint(str(tmp_path), tag="off")
        cont = [float(engine.train_batch(batch)["loss"]) for _ in range(2)]
        e2, *_ = deepspeed_tpu.initialize(model=tiny_model(), config=cfg)
        e2.load_checkpoint(str(tmp_path), tag="off")
        resumed = [float(e2.train_batch(batch)["loss"]) for _ in range(2)]
        np.testing.assert_allclose(cont, resumed, rtol=2e-4, atol=1e-5)


class TestAIO:
    def test_roundtrip(self):
        from deepspeed_tpu.ops.aio import AIOHandle, aio_available
        if not aio_available():
            pytest.skip("no g++/native build")
        h = AIOHandle(block_size=1 << 16, queue_depth=8, thread_count=2)
        x = np.random.default_rng(0).standard_normal((1000, 333)).astype(np.float32)
        path = os.path.join(tempfile.mkdtemp(), "t.bin")
        h.pwrite(path, x)
        y = h.pread(path, x.shape, x.dtype)
        np.testing.assert_array_equal(x, y)

    def test_offset_io(self):
        from deepspeed_tpu.ops.aio import AIOHandle, aio_available
        if not aio_available():
            pytest.skip("no g++/native build")
        h = AIOHandle()
        a = np.arange(512, dtype=np.int32)
        b = np.arange(512, 1024, dtype=np.int32)
        path = os.path.join(tempfile.mkdtemp(), "o.bin")
        h.pwrite(path, a, file_offset=0)
        h.pwrite(path, b, file_offset=a.nbytes)
        got = h.pread(path, (1024,), np.int32)
        np.testing.assert_array_equal(got, np.arange(1024, dtype=np.int32))

    def test_unaligned_sizes(self):
        from deepspeed_tpu.ops.aio import AIOHandle, aio_available
        if not aio_available():
            pytest.skip("no g++/native build")
        h = AIOHandle(block_size=1 << 12)
        x = np.random.default_rng(1).bytes(12345)
        arr = np.frombuffer(x, dtype=np.uint8)
        path = os.path.join(tempfile.mkdtemp(), "u.bin")
        h.pwrite(path, arr)
        y = h.pread(path, arr.shape, np.uint8)
        np.testing.assert_array_equal(arr, y)


class TestNVMeOffload:
    """ZeRO-Infinity optimizer-state swapping (reference:
    swap_tensor/partitioned_optimizer_swapper.py, pipelined_optimizer_swapper.py)."""

    def _cfg(self, tmp, extra=None):
        cfg = {"train_batch_size": 16,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "bf16": {"enabled": False}, "steps_per_print": 1000,
               "gradient_clipping": 1.0,
               "zero_optimization": {
                   "stage": 3,
                   "offload_optimizer": {"device": "nvme",
                                         "nvme_path": str(tmp),
                                         # tiny buffer -> several chunks
                                         "buffer_size": 4 * 4096}}}
        if extra:
            cfg.update(extra)
        return cfg

    def test_nvme_matches_in_hbm_baseline(self, tmp_path):
        base = {"train_batch_size": 16,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                "bf16": {"enabled": False}, "steps_per_print": 1000,
                "gradient_clipping": 1.0,
                "zero_optimization": {"stage": 3}}
        e1, *_ = deepspeed_tpu.initialize(model=tiny_model(), config=base)
        e2, *_ = deepspeed_tpu.initialize(model=tiny_model(),
                                          config=self._cfg(tmp_path))
        assert e2._swapper is not None and e2._swapper.n_chunks > 1
        assert e2.state["opt"] is None  # no fp32 state in device memory
        batch = make_batch(16, 32, vocab=64)
        l1 = [float(e1.train_batch(batch)["loss"]) for _ in range(6)]
        l2 = [float(e2.train_batch(batch)["loss"]) for _ in range(6)]
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-5)
        p1 = jax.tree.leaves(e1.state["params"])[0]
        p2 = jax.tree.leaves(e2.state["params"])[0]
        np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                                   rtol=1e-4, atol=1e-6)

    def test_nvme_checkpoint_roundtrip(self, tmp_path):
        ck = tmp_path / "ck"
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_model(), config=self._cfg(tmp_path / "swap"))
        batch = make_batch(16, 32, vocab=64)
        for _ in range(3):
            engine.train_batch(batch)
        engine.save_checkpoint(str(ck), tag="nv")
        cont = [float(engine.train_batch(batch)["loss"]) for _ in range(2)]
        e2, *_ = deepspeed_tpu.initialize(
            model=tiny_model(), config=self._cfg(tmp_path / "swap2"))
        e2.load_checkpoint(str(ck), tag="nv")
        resumed = [float(e2.train_batch(batch)["loss"]) for _ in range(2)]
        np.testing.assert_allclose(cont, resumed, rtol=2e-4, atol=1e-5)

    def test_nvme_requires_path_and_adam(self, tmp_path):
        bad = {"train_batch_size": 8,
               "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": 3,
                                     "offload_optimizer": {"device": "nvme"}}}
        with pytest.raises(Exception, match="nvme_path"):
            deepspeed_tpu.initialize(model=tiny_model(), config=bad)
        bad2 = {"train_batch_size": 8,
                "optimizer": {"type": "sgd", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 3,
                                      "offload_optimizer": {
                                          "device": "nvme",
                                          "nvme_path": str(tmp_path)}}}
        with pytest.raises(Exception, match="[Aa]dam"):
            deepspeed_tpu.initialize(model=tiny_model(), config=bad2)


class TestHostCPUAdam:
    """offload_optimizer.use_cpu_adam: the optimizer runs ON the host via
    the native fused CPU-Adam (reference: DeepSpeedCPUAdam); only compute-
    dtype grads/params cross the bus."""

    def _cfg(self, clip=0.0):
        return {"train_batch_size": 16,
                "optimizer": {"type": "adamw",
                              "params": {"lr": 1e-2, "weight_decay": 0.01}},
                "bf16": {"enabled": False}, "steps_per_print": 1000,
                "gradient_clipping": clip,
                "zero_optimization": {"stage": 1,
                                      "offload_optimizer": {
                                          "device": "cpu",
                                          "use_cpu_adam": True}}}

    def test_matches_baseline(self):
        from deepspeed_tpu.ops.cpu_adam import cpu_adam_available
        if not cpu_adam_available():
            pytest.skip("native cpu_adam unavailable")
        base = {"train_batch_size": 16,
                "optimizer": {"type": "adamw",
                              "params": {"lr": 1e-2, "weight_decay": 0.01}},
                "bf16": {"enabled": False}, "steps_per_print": 1000}
        e1, *_ = deepspeed_tpu.initialize(model=tiny_model(), config=base)
        e2, *_ = deepspeed_tpu.initialize(model=tiny_model(),
                                          config=self._cfg())
        assert e2._swap_storage == "cpu_adam"
        batch = make_batch(16, 32, vocab=64)
        l1 = [float(e1.train_batch(batch)["loss"]) for _ in range(5)]
        l2 = [float(e2.train_batch(batch)["loss"]) for _ in range(5)]
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-5)

    def test_clip_and_checkpoint_roundtrip(self, tmp_path):
        from deepspeed_tpu.ops.cpu_adam import cpu_adam_available
        if not cpu_adam_available():
            pytest.skip("native cpu_adam unavailable")
        engine, *_ = deepspeed_tpu.initialize(model=tiny_model(),
                                              config=self._cfg(clip=0.5))
        batch = make_batch(16, 32, vocab=64)
        for _ in range(3):
            m = engine.train_batch(batch)
        assert float(m["grad_norm"]) > 0
        engine.save_checkpoint(str(tmp_path), tag="ha")
        cont = [float(engine.train_batch(batch)["loss"]) for _ in range(2)]
        e2, *_ = deepspeed_tpu.initialize(model=tiny_model(),
                                          config=self._cfg(clip=0.5))
        e2.load_checkpoint(str(tmp_path), tag="ha")
        resumed = [float(e2.train_batch(batch)["loss"]) for _ in range(2)]
        np.testing.assert_allclose(cont, resumed, rtol=2e-4, atol=1e-5)


class TestHostCPUAdagrad:
    """Host Adagrad tier (reference: DeepSpeedCPUAdagrad over
    csrc/adagrad/cpu_adagrad.cpp): offload_optimizer.use_cpu_adam with an
    adagrad optimizer routes to the native host Adagrad."""

    def _cfg(self):
        return {"train_batch_size": 16,
                "optimizer": {"type": "adagrad",
                              "params": {"lr": 1e-2}},
                "bf16": {"enabled": False}, "steps_per_print": 1000,
                "zero_optimization": {"stage": 1,
                                      "offload_optimizer": {
                                          "device": "cpu",
                                          "use_cpu_adam": True}}}

    def test_kernel_parity_vs_traced_adagrad(self):
        """The native flat kernel == the traced ops.optimizers adagrad
        math on random buffers (both dtypes of the grad wire)."""
        from deepspeed_tpu.ops.cpu_adagrad import (adagrad_step_flat,
                                                   cpu_adagrad_available)
        if not cpu_adagrad_available():
            pytest.skip("native cpu_adagrad unavailable")
        import ml_dtypes
        rng = np.random.default_rng(0)
        n = 4097
        master = rng.normal(size=n).astype(np.float32)
        accum = np.abs(rng.normal(size=n)).astype(np.float32)
        g32 = rng.normal(size=n).astype(np.float32)
        ref_g = g32 + 0.01 * master
        ref_accum = accum + ref_g * ref_g
        ref_master = master - 1e-2 * ref_g / (np.sqrt(ref_accum) + 1e-10)
        m2, a2 = master.copy(), accum.copy()
        out = np.empty(n, np.float32)
        adagrad_step_flat(m2, a2, g32, lr=1e-2, eps=1e-10,
                          weight_decay=0.01, out=out)
        np.testing.assert_allclose(m2, ref_master, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(a2, ref_accum, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(out, ref_master, rtol=1e-6, atol=1e-7)
        # bf16-bits wire
        gb = g32.astype(ml_dtypes.bfloat16)
        m3, a3 = master.copy(), accum.copy()
        out16 = np.empty(n, np.uint16)
        adagrad_step_flat(m3, a3, gb.view(np.uint16), lr=1e-2, eps=1e-10,
                          weight_decay=0.01, out=out16)
        g16 = gb.astype(np.float32) + 0.01 * master
        acc16 = accum + g16 * g16
        ref16 = master - 1e-2 * g16 / (np.sqrt(acc16) + 1e-10)
        np.testing.assert_allclose(m3, ref16, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(
            out16.view(ml_dtypes.bfloat16).astype(np.float32),
            ref16, rtol=1e-2, atol=1e-3)

    def test_matches_baseline_engine(self):
        from deepspeed_tpu.ops.cpu_adagrad import cpu_adagrad_available
        if not cpu_adagrad_available():
            pytest.skip("native cpu_adagrad unavailable")
        base = {"train_batch_size": 16,
                "optimizer": {"type": "adagrad", "params": {"lr": 1e-2}},
                "bf16": {"enabled": False}, "steps_per_print": 1000}
        e1, *_ = deepspeed_tpu.initialize(model=tiny_model(), config=base)
        e2, *_ = deepspeed_tpu.initialize(model=tiny_model(),
                                          config=self._cfg())
        assert e2._swap_storage == "cpu_adam"
        assert e2._swapper is not None and e2._swapper.optim == "adagrad"
        batch = make_batch(16, 32, vocab=64)
        l1 = [float(e1.train_batch(batch)["loss"]) for _ in range(5)]
        l2 = [float(e2.train_batch(batch)["loss"]) for _ in range(5)]
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=1e-5)

    def test_checkpoint_roundtrip(self, tmp_path):
        from deepspeed_tpu.ops.cpu_adagrad import cpu_adagrad_available
        if not cpu_adagrad_available():
            pytest.skip("native cpu_adagrad unavailable")
        engine, *_ = deepspeed_tpu.initialize(model=tiny_model(),
                                              config=self._cfg())
        batch = make_batch(16, 32, vocab=64)
        for _ in range(3):
            engine.train_batch(batch)
        engine.save_checkpoint(str(tmp_path), tag="hag")
        cont = [float(engine.train_batch(batch)["loss"]) for _ in range(2)]
        e2, *_ = deepspeed_tpu.initialize(model=tiny_model(),
                                          config=self._cfg())
        e2.load_checkpoint(str(tmp_path), tag="hag")
        resumed = [float(e2.train_batch(batch)["loss"]) for _ in range(2)]
        np.testing.assert_allclose(cont, resumed, rtol=2e-4, atol=1e-5)
