"""1-bit optimizer + compressed collective tests.

Reference behavior: ``runtime/fp16/onebit/{adam,lamb,zoadam}.py`` and the
compressed allreduce of ``runtime/comm/nccl.py:53`` — warmup must equal the
dense optimizer exactly, the compressed stage must converge, and (the entire
point) the compressed stage must move ~1/32nd the bytes of a dense reduce.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm.comm import comms_logger
from deepspeed_tpu.comm.compressed import (
    compressed_allreduce_1bit, pack_signs, unpack_signs)
from deepspeed_tpu.models import TransformerConfig, make_model
from tests.conftest import make_batch

# quick tier: `pytest -m 'not slow'` skips this module (phased shard_map steps compile per phase)
pytestmark = pytest.mark.slow


def test_pack_unpack_roundtrip():
    x = np.random.default_rng(0).normal(size=(1000,)).astype(np.float32)
    packed, n = pack_signs(jnp.asarray(x))
    assert packed.dtype == jnp.uint8 and packed.size == 125
    signs = np.asarray(unpack_signs(packed, n))
    np.testing.assert_array_equal(signs, np.where(x >= 0, 1.0, -1.0))


def test_compressed_allreduce_parity(devices8):
    """Inside shard_map over 8 ranks: result == mean_i(sign(x_i)*scale_i),
    identical on every rank."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()), ("d",))
    x = np.random.default_rng(1).normal(size=(8, 33)).astype(np.float32)

    out = jax.shard_map(
        lambda xs: compressed_allreduce_1bit(xs[0], "d")[None],
        mesh=mesh, in_specs=P("d"), out_specs=P("d"))(x)
    out = np.asarray(out)
    expect = np.mean(
        [np.where(x[i] >= 0, 1.0, -1.0) * np.abs(x[i]).mean()
         for i in range(8)], axis=0)
    for i in range(8):
        np.testing.assert_allclose(out[i], expect, rtol=1e-5, atol=1e-7)


def _engine(opt_name, devices=None, freeze_kw=None, **cfg_over):
    model = make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
    params = {"lr": 1e-2}
    params.update(freeze_kw or {})
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": opt_name, "params": params},
           "bf16": {"enabled": False}, "steps_per_print": 1000}
    cfg.update(cfg_over)
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


class TestOnebitAdamEngine:
    def test_warmup_matches_dense_adam(self, devices8):
        """During warmup the compressed path IS dense Adam — loss curves must
        match the plain adam engine exactly."""
        b = make_batch(16, 32, vocab=64, seed=0)
        e1 = _engine("adam", freeze_kw={"weight_decay": 0.0})
        l1 = [float(e1.train_batch(b)["loss"]) for _ in range(4)]
        e2 = _engine("onebitadam", freeze_kw={"freeze_step": 100})
        assert e2._onebit_comm, "pure-dp stage-0 engine must take the compressed path"
        l2 = [float(e2.train_batch(b)["loss"]) for _ in range(4)]
        np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=1e-6)

    def test_compressed_stage_converges_and_saves_bytes(self, devices8):
        b = make_batch(16, 32, vocab=64, seed=1)
        comms_logger.configure(enabled=True)
        comms_logger.reset()
        # sign updates oscillate at high lr on this toy loss; 2e-3 converges
        e = _engine("onebitadam", freeze_kw={"lr": 2e-3, "freeze_step": 3})
        losses = [float(e.train_batch(b)["loss"]) for _ in range(10)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
        stats = dict(comms_logger.bytes)
        comms_logger.configure(enabled=False)
        dense = sum(v for k, v in stats.items() if k.startswith("pmean_dense"))
        packed = sum(v for k, v in stats.items()
                     if k.startswith("all_gather_1bit"))
        assert packed > 0, stats
        # one warm trace + one compressed trace of the same tree: the packed
        # volume must be ~1/32nd of the dense f32 volume
        assert packed < dense / 20, (packed, dense)

    def test_rank_varying_error_state(self, devices8):
        """The error-feedback buffer carries an explicit [dp] leading dim
        sharded over data — per-worker values, checkpointable."""
        e = _engine("onebitadam", freeze_kw={"freeze_step": 2})
        err = jax.tree.leaves(e.state["opt"]["error"])[0]
        assert err.shape[0] == 8
        b = make_batch(16, 32, vocab=64, seed=2)
        for _ in range(5):
            e.train_batch(b)
        # after compressed steps the per-rank errors genuinely differ
        err = np.asarray(jax.device_get(jax.tree.leaves(e.state["opt"]["error"])[1]))
        assert err.shape[0] == 8
        assert not np.allclose(err[0], err[1])

    def test_fallback_when_not_pure_dp(self, devices8):
        e = _engine("onebitadam", freeze_kw={"freeze_step": 2},
                    tensor_parallel={"size": 2})
        assert not e._onebit_comm
        b = make_batch(16, 32, vocab=64, seed=3)
        losses = [float(e.train_batch(b)["loss"]) for _ in range(4)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]


class TestOnebitLamb:
    def test_trains_through_freeze(self, devices8):
        b = make_batch(16, 32, vocab=64, seed=4)
        e = _engine("onebitlamb", freeze_kw={"freeze_step": 3})
        assert e._onebit_comm
        losses = [float(e.train_batch(b)["loss"]) for _ in range(8)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        # frozen trust ratios captured during warmup
        ratios = jax.tree.leaves(e.state["opt"]["frozen_ratio"])
        assert all(np.isfinite(float(np.asarray(jax.device_get(r))))
                   for r in ratios)


class TestZeroOneAdam:
    def test_local_steps_skip_communication(self, devices8):
        """0/1 Adam: the 'local' phase program contains NO collective at all
        (checked in the compiled HLO), and training still converges."""
        b = make_batch(16, 32, vocab=64, seed=5)
        e = _engine("zerooneadam",
                    freeze_kw={"lr": 2e-3, "var_freeze_step": 6,
                               "local_step_scaler": 2,
                               "local_step_clipper": 4})
        losses = [float(e.train_batch(b)["loss"]) for _ in range(16)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        assert set(e._onebit_steps) >= {"dense", "local", "sync"}
        local_hlo = e._onebit_steps["local"].lower(
            e.state, e._device_batch(b), jax.random.PRNGKey(0)
        ).compile().as_text()
        # only scalar metric reductions (loss/grad-norm pmean) may remain;
        # no tensor-sized collective = no gradient/momentum traffic
        import re
        ar_shapes = re.findall(r"(\w+\[[\d,]*\])[^=\n]*= all-reduce", local_hlo)
        assert all(re.fullmatch(r"\w+\[\]", s) for s in ar_shapes), ar_shapes
        assert "all-gather" not in local_hlo

    def test_dense_fallback_zero1(self, devices8):
        """With ZeRO-1 the compressed path is ineligible; the dense
        single-program fallback (variance freeze only) still trains."""
        e = _engine("zerooneadam", freeze_kw={"lr": 2e-3,
                                              "var_freeze_step": 6},
                    zero_optimization={"stage": 1})
        assert not e._onebit_comm
        b = make_batch(16, 32, vocab=64, seed=6)
        losses = [float(e.train_batch(b)["loss"]) for _ in range(10)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]


class TestOnebitFp16Clip:
    """Round-3 widening (reference: fp16/onebit/adam.py is fp16-native):
    fp16 loss scaling + overflow skip inside the shard_map step, and
    synchronized norm-proxy gradient clipping before the compressed
    exchange."""

    def test_fp16_takes_compressed_path_and_converges(self, devices8):
        b = make_batch(16, 32, vocab=64, seed=4)
        comms_logger.configure(enabled=True)
        comms_logger.reset()
        e = _engine("onebitadam", freeze_kw={"lr": 2e-3, "freeze_step": 3},
                    **{"bf16": {"enabled": False},
                       "fp16": {"enabled": True, "loss_scale": 0.0,
                                "initial_scale_power": 8}})
        assert e._onebit_comm and e._fp16
        losses, scales = [], []
        for _ in range(10):
            m = e.train_batch(b)
            losses.append(float(m["loss"]))
            scales.append(float(m["loss_scale"]))
        stats = dict(comms_logger.bytes)
        comms_logger.configure(enabled=False)
        assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
        assert scales[0] == 2.0 ** 8
        # compressed-phase wire volume < 1/16 of one dense f32 exchange
        dense = sum(v for k, v in stats.items()
                    if k.startswith("pmean_dense"))
        packed = sum(v for k, v in stats.items()
                     if k.startswith("all_gather_1bit"))
        assert packed > 0 and packed < dense / 16, (packed, dense)

    def test_fp16_overflow_skips_and_shrinks_scale(self, devices8):
        e = _engine("onebitadam", freeze_kw={"lr": 1e-3, "freeze_step": 2},
                    **{"bf16": {"enabled": False},
                       "fp16": {"enabled": True, "loss_scale": 0.0,
                                "initial_scale_power": 40,
                                "hysteresis": 1}})
        # 2^40 loss scale overflows fp32 grads immediately
        b = make_batch(16, 32, vocab=64, seed=5)
        p_before = np.asarray(jax.device_get(
            jax.tree.leaves(e.state["params"])[0]))
        m = e.train_batch(b)
        assert bool(m["overflow"])
        assert e.skipped_steps == 1
        p_after = np.asarray(jax.device_get(
            jax.tree.leaves(e.state["params"])[0]))
        np.testing.assert_array_equal(p_before, p_after)  # step skipped
        # dynamic scale halves after the overflow
        assert float(np.asarray(jax.device_get(
            e.state["loss_scale"]["scale"]))) < 2.0 ** 40

    def test_clipping_applies_and_stays_synchronized(self, devices8):
        b = make_batch(16, 32, vocab=64, seed=6)
        e = _engine("onebitadam", freeze_kw={"lr": 2e-3, "freeze_step": 2},
                    gradient_clipping=0.05)
        losses = [float(e.train_batch(b)["loss"]) for _ in range(6)]
        assert np.isfinite(losses).all()
        # params remain REPLICATED (identical) across the 8 ranks after
        # compressed steps with clipping — the sync invariant
        leaf = jax.tree.leaves(e.state["params"])[1]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


class TestOnebitCompression:
    """compression_training composes with the 1-bit compressed-comm path
    (VERDICT r4 item 8): the shard_map step applies the same traced param
    transform as the GSPMD step applies in micro_grads."""

    COMP = {"weight_quantization": {
        "shared_parameters": {"enabled": True, "schedule_offset": 0},
        "different_groups": {
            "q8": {"params": {"target_bits": 8}, "modules": ["*"]}}}}

    def test_warmup_matches_dense_with_compression(self, devices8):
        """Warmup phase == dense Adam, both under the same weight-quant
        transform — loss curves must match the GSPMD engine exactly."""
        b = make_batch(16, 32, vocab=64, seed=4)
        e1 = _engine("adam", freeze_kw={"weight_decay": 0.0},
                     compression_training=self.COMP)
        l1 = [float(e1.train_batch(b)["loss"]) for _ in range(4)]
        e2 = _engine("onebitadam", freeze_kw={"freeze_step": 100},
                     compression_training=self.COMP)
        assert e2._onebit_comm and e2._compression is not None
        l2 = [float(e2.train_batch(b)["loss"]) for _ in range(4)]
        np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=1e-6)
        # the transform is live: quantized forward differs from a no-comp run
        e3 = _engine("onebitadam", freeze_kw={"freeze_step": 100})
        l3 = float(e3.train_batch(b)["loss"])
        assert abs(l3 - l2[0]) > 1e-6

    def test_compressed_stage_with_compression_converges(self, devices8):
        b = make_batch(16, 32, vocab=64, seed=5)
        e = _engine("onebitadam", freeze_kw={"lr": 2e-3, "freeze_step": 3},
                    compression_training=self.COMP)
        losses = [float(e.train_batch(b)["loss"]) for _ in range(8)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]


class TestOnebitMoQ:
    """quantize_training (MoQ) composes with the 1-bit compressed-comm
    path: the shard_map step applies the traced _moq_bits transform
    (replicated side-channel — its leading dim is the LAYER count, not
    the batch) inside its per-device loss."""

    MOQ = {"enabled": True,
           "quantize_bits": {"start_bits": 6, "target_bits": 4},
           "quantize_schedule": {"quantize_period": 4}}

    def test_warmup_matches_dense_with_moq(self, devices8):
        b = make_batch(16, 32, vocab=64, seed=6)
        e1 = _engine("adam", freeze_kw={"weight_decay": 0.0},
                     quantize_training=self.MOQ)
        l1 = [float(e1.train_batch(b)["loss"]) for _ in range(4)]
        e2 = _engine("onebitadam", freeze_kw={"freeze_step": 100},
                     quantize_training=self.MOQ)
        assert e2._onebit_comm and e2._moq is not None
        l2 = [float(e2.train_batch(b)["loss"]) for _ in range(4)]
        np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=1e-6)
        # the transform is live: 6-bit fake-quant shifts the loss vs no-MoQ
        e3 = _engine("onebitadam", freeze_kw={"freeze_step": 100})
        l3 = float(e3.train_batch(b)["loss"])
        assert abs(l3 - l2[0]) > 1e-5

    def test_compressed_stage_with_moq_converges(self, devices8):
        b = make_batch(16, 32, vocab=64, seed=7)
        e = _engine("onebitadam", freeze_kw={"lr": 2e-3, "freeze_step": 3},
                    quantize_training=self.MOQ)
        losses = [float(e.train_batch(b)["loss"]) for _ in range(8)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        # the schedule advanced toward target bits during the run
        assert e._moq.bits(e.global_steps).max() < 6
