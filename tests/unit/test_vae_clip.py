"""VAE + CLIP — the diffusers corner (reference:
model_implementations/diffusers/vae.py DSVAE encode/decode,
module_inject/containers/clip.py HFCLIPLayerPolicy for BOTH towers), plus
the latent-diffusion smoke chaining CLIP -> UNet -> VAE under
init_inference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import (
    VAEConfig, make_vae_model, vae_encode, vae_decode,
    UNetConfig, make_unet_model, unet_forward,
    CLIPVisionSpec, make_clip_vision_model, clip_vision_encode,
    load_clip_vision_params, vision_transformer_config,
    TransformerConfig, make_model, load_hf_params, hf_config_to_transformer,
)

pytestmark = pytest.mark.slow   # conv mesh + HF model compiles


def _vae_cfg():
    return VAEConfig(base_channels=16, channel_mults=(1, 2),
                     num_res_blocks=1, latent_channels=4, norm_groups=4,
                     dtype=jnp.float32, param_dtype=jnp.float32)


class TestVAE:
    def test_encode_decode_shapes(self):
        cfg = _vae_cfg()
        model = make_vae_model(cfg)
        params = model.init(jax.random.PRNGKey(0))
        x = np.random.default_rng(0).normal(size=(2, 16, 16, 3)) \
            .astype(np.float32)
        mean, logvar = vae_encode(params, jnp.asarray(x), cfg)
        assert mean.shape == (2, 8, 8, 4) and logvar.shape == mean.shape
        img = vae_decode(params, mean, cfg)
        assert img.shape == (2, 16, 16, 3)
        assert np.isfinite(np.asarray(img)).all()

    def test_trains_under_zero(self):
        cfg = _vae_cfg()
        engine, *_ = deepspeed_tpu.initialize(
            model=make_vae_model(cfg), config={
                "train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 2e-3}},
                "zero_optimization": {"stage": 2},
                "bf16": {"enabled": False},
                "steps_per_print": 1000000})
        r = np.random.default_rng(0)
        batch = {"x": r.normal(size=(8, 16, 16, 3)).astype(np.float32)}
        losses = [float(engine.train_batch(batch)["loss"])
                  for _ in range(8)]
        assert losses[-1] < losses[0], losses

    def test_runs_under_init_inference(self):
        cfg = _vae_cfg()
        eng = deepspeed_tpu.init_inference(make_vae_model(cfg),
                                           dtype=jnp.float32)
        x = np.random.default_rng(1).normal(size=(1, 16, 16, 3)) \
            .astype(np.float32)
        out = np.asarray(eng.forward(x))
        assert out.shape == (1, 16, 16, 3) and np.isfinite(out).all()


class TestCLIPText:
    def test_import_hidden_parity(self):
        transformers = pytest.importorskip("transformers")
        import torch
        hf_cfg = transformers.CLIPTextConfig(
            vocab_size=99, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=24)
        hf = transformers.CLIPTextModel(hf_cfg).eval()
        cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                       attention_impl="xla")
        assert cfg.causal and cfg.activation == "quick_gelu"
        params = load_hf_params(hf, cfg)
        ids = np.random.default_rng(0).integers(0, 99, (2, 16),
                                                dtype=np.int32)
        from deepspeed_tpu.models.transformer import forward
        ours = np.asarray(forward(params, jnp.asarray(ids), cfg,
                                  return_hidden=True)[0])
        with torch.no_grad():
            ref = hf(torch.from_numpy(ids).long()) \
                .last_hidden_state.float().numpy()
        np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_engine_encode(self):
        transformers = pytest.importorskip("transformers")
        hf_cfg = transformers.CLIPTextConfig(
            vocab_size=99, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=24)
        hf = transformers.CLIPTextModel(hf_cfg).eval()
        cfg = hf_config_to_transformer(hf_cfg, dtype=jnp.float32,
                                       attention_impl="xla")
        params = load_hf_params(hf, cfg)
        eng = deepspeed_tpu.init_inference(
            make_model(cfg, name="clip-text"), params=params,
            dtype=jnp.float32)
        ids = np.random.default_rng(0).integers(0, 99, (2, 16),
                                                dtype=np.int32)
        h = np.asarray(eng.encode(ids))
        assert h.shape == (2, 16, 32) and np.isfinite(h).all()


class TestCLIPVision:
    def test_import_hidden_parity(self):
        transformers = pytest.importorskip("transformers")
        import torch
        hf_cfg = transformers.CLIPVisionConfig(
            hidden_size=32, intermediate_size=64, num_hidden_layers=2,
            num_attention_heads=4, image_size=32, patch_size=16)
        hf = transformers.CLIPVisionModel(hf_cfg).eval()
        tcfg = vision_transformer_config(
            image_size=32, patch_size=16, hidden_size=32, num_layers=2,
            num_heads=4, intermediate_size=64)
        spec = CLIPVisionSpec(image_size=32, patch_size=16, tcfg=tcfg)
        params = load_clip_vision_params(hf, spec)
        px = np.random.default_rng(0).normal(size=(2, 32, 32, 3)) \
            .astype(np.float32)
        ours = np.asarray(clip_vision_encode(params, px, spec))
        with torch.no_grad():
            # HF takes NCHW
            ref = hf(torch.from_numpy(px.transpose(0, 3, 1, 2))) \
                .last_hidden_state.float().numpy()
        np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)

    def test_runs_under_init_inference(self):
        tcfg = vision_transformer_config(
            image_size=32, patch_size=16, hidden_size=32, num_layers=2,
            num_heads=4, intermediate_size=64)
        spec = CLIPVisionSpec(image_size=32, patch_size=16, tcfg=tcfg)
        eng = deepspeed_tpu.init_inference(make_clip_vision_model(spec),
                                           dtype=jnp.float32)
        px = np.random.default_rng(1).normal(size=(1, 32, 32, 3)) \
            .astype(np.float32)
        out = np.asarray(eng.forward(px))
        assert out.shape == (1, 5, 32) and np.isfinite(out).all()


class TestLatentDiffusionSmoke:
    def test_clip_unet_vae_chain(self):
        """The SD pipeline shape under init_inference: text encode (CLIP)
        -> denoise a latent with the conditioned UNet -> decode the latent
        (VAE). Matches the reference's injection set {clip, unet, vae}."""
        # CLIP text tower (random weights — the chain is the contract)
        tcfg = TransformerConfig(
            vocab_size=99, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=24, position_type="learned",
            activation="quick_gelu", norm_type="layernorm", causal=True,
            qkv_bias=True, final_norm=True, tie_embeddings=True,
            dtype=jnp.float32, attention_impl="xla")
        text_eng = deepspeed_tpu.init_inference(
            make_model(tcfg, name="clip-text"), dtype=jnp.float32)
        ids = np.random.default_rng(0).integers(0, 99, (2, 16),
                                                dtype=np.int32)
        context = text_eng.encode(ids)                    # [2, 16, 32]

        vcfg = _vae_cfg()
        vae_eng = deepspeed_tpu.init_inference(make_vae_model(vcfg),
                                               dtype=jnp.float32)

        ucfg = UNetConfig(in_channels=4, out_channels=4, base_channels=16,
                          channel_mults=(1, 2), num_res_blocks=1,
                          time_embed_dim=32, attn_heads=4, norm_groups=4,
                          context_dim=32, dtype=jnp.float32,
                          param_dtype=jnp.float32)
        unet_eng = deepspeed_tpu.init_inference(make_unet_model(ucfg),
                                                dtype=jnp.float32)

        # round-trip an image through the engine's DSVAE surface
        img_in = np.random.default_rng(5).normal(
            size=(2, 16, 16, 3)).astype(np.float32)
        lat = vae_eng.vae_encode(img_in)
        assert np.asarray(lat).shape == (2, 8, 8, 4)

        # one denoising step on an 8x8x4 latent, conditioned on the text
        # — THROUGH the engine's jitted kwarg-carrying forward
        z = jnp.asarray(np.random.default_rng(1).normal(
            size=(2, 8, 8, 4)).astype(np.float32))
        t = jnp.asarray([10, 10], jnp.int32)
        eps = unet_eng.forward(z, t=t, context=context)
        assert np.asarray(eps).shape == z.shape
        z0 = z - 0.1 * jnp.asarray(eps)                    # toy update
        img = vae_eng.vae_decode(z0)
        assert np.asarray(img).shape == (2, 16, 16, 3)
        assert np.isfinite(np.asarray(img)).all()
        # conditioning is live: different text -> different eps
        ids2 = np.random.default_rng(7).integers(0, 99, (2, 16),
                                                 dtype=np.int32)
        ctx2 = text_eng.encode(ids2)
        eps2 = unet_eng.forward(z, t=t, context=ctx2)
        assert not np.allclose(np.asarray(eps), np.asarray(eps2))
        # a conditioned UNet REFUSES to run unconditioned (SD semantics:
        # the unconditional branch uses null-text embeddings)
        with pytest.raises(Exception, match="context"):
            unet_eng.forward(z, t=t)
