"""Serving engine: continuous batching + paged KV cache + quantized decode.

Reference behavior being exceeded: SURVEY §6's InferenceEngine serves one
shape-bucketed batch per generate() call; the serving tier admits/evicts at
decode-step boundaries over a shared block pool. The load-bearing contracts
pinned here:

  - paged decode is BIT-FOR-BIT the contiguous ring-buffer decode (same
    einsums on a gathered view — greedy tokens AND logits identical over
    20+ steps, float and int8-KV caches);
  - the scheduler admits FIFO, evicts on finish, preempts newest-first
    under pool pressure, and queues gracefully on exhaustion (never OOM);
  - the Pallas paged kernel and the XLA gather agree (backend is a
    measured choice, logged as a telemetry event, never silently wrong);
  - a leaked block pool is a lint failure (`paged-cache-leak` corpus).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.kv_cache import (BlockAllocator,
                                              BlockPoolExhausted, blocks_for)
from deepspeed_tpu.inference.scheduler import RequestScheduler
from deepspeed_tpu.inference.serving import ServingConfig, ServingEngine
from deepspeed_tpu.models import TransformerConfig, make_model


def _cfg(**overrides):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, max_seq_len=256, position_type="rotary",
                activation="silu_glu", norm_type="rmsnorm",
                tie_embeddings=False, dtype=jnp.float32,
                attention_impl="xla")
    base.update(overrides)
    return TransformerConfig(**base)


# ---------------------------------------------------------------------------
# Block allocator (pure host)
# ---------------------------------------------------------------------------

class TestBlockAllocator:
    def test_block0_reserved_and_lifo_reuse(self):
        a = BlockAllocator(8)
        assert a.free_blocks == 7           # block 0 never in the free list
        got = a.alloc(3)
        assert 0 not in got
        a.free(got)
        assert a.alloc(1) == [got[-1]]      # LIFO: warmest block first

    def test_exhaustion_raises_typed(self):
        a = BlockAllocator(4)
        a.alloc(3)
        assert not a.can_alloc(1)
        with pytest.raises(BlockPoolExhausted):
            a.alloc(1)

    def test_double_free_and_trash_free_raise(self):
        a = BlockAllocator(4)
        ids = a.alloc(2)
        a.free(ids)
        with pytest.raises(ValueError, match="double free"):
            a.free([ids[0]])
        with pytest.raises(ValueError, match="trash"):
            a.free([0])

    def test_blocks_for(self):
        assert blocks_for(0, 16) == 0
        assert blocks_for(1, 16) == 1
        assert blocks_for(16, 16) == 1
        assert blocks_for(17, 16) == 2


# ---------------------------------------------------------------------------
# Scheduler (pure host: admit / evict / preempt ordering)
# ---------------------------------------------------------------------------

def _sched(num_blocks=32, max_seqs=4, bs=16, quantum=4, mb=8):
    alloc = BlockAllocator(num_blocks)
    return alloc, RequestScheduler(
        alloc, max_seqs, bs, quantum,
        prompt_blocks=lambda n: blocks_for(max(n, bs), bs),
        max_blocks_per_seq=mb)


class TestScheduler:
    def test_fifo_admission_order(self):
        _, s = _sched()
        reqs = [s.submit(np.arange(10), 8) for _ in range(3)]
        out = s.schedule()
        assert out["admitted"] == reqs      # arrival order
        assert [r.state for r in reqs] == ["running"] * 3

    def test_slot_limit_queues(self):
        _, s = _sched(max_seqs=2)
        reqs = [s.submit(np.arange(10), 8) for _ in range(3)]
        out = s.schedule()
        assert len(out["admitted"]) == 2
        assert s.num_waiting == 1 and reqs[2].state == "waiting"

    def test_pool_exhaustion_queues_not_raises(self):
        # 9 usable blocks; each request needs ceil((32+4)/16)=3 -> 3 admit
        alloc, s = _sched(num_blocks=10, max_seqs=8)
        reqs = [s.submit(np.arange(32), 8) for _ in range(5)]
        out = s.schedule()
        assert len(out["admitted"]) == 3
        assert s.num_waiting == 2
        assert alloc.free_blocks == 0
        # finishing one frees its blocks and the queue head admits next
        s.finish(reqs[0])
        out = s.schedule()
        assert out["admitted"] == [reqs[3]]

    def test_growth_preempts_newest_first(self):
        # two running, pool exactly covers their prompts; growth pressure
        # must preempt the NEWEST and keep the oldest progressing
        alloc, s = _sched(num_blocks=7, max_seqs=4, bs=16, quantum=4)
        r1 = s.submit(np.arange(30), 64)    # 3 blocks (ctx+quantum=34)
        r2 = s.submit(np.arange(30), 64)
        assert len(s.schedule()["admitted"]) == 2
        assert alloc.free_blocks == 0
        # simulate r1 decoding to the edge of its coverage
        r1.cached_rows = 46                 # needs blocks_for(50)=4 next
        r1.generated = list(range(16))
        out = s.schedule()
        assert out["preempted"] == [r2]
        assert r2.state == "waiting" and r2.preemptions == 1
        assert len(r1.block_ids) == 4       # oldest got its growth
        # the preempted request resumes at the FRONT of the queue with its
        # generated tokens intact (re-prefill recomputes its rows)
        r3 = s.submit(np.arange(8), 8)
        assert s.waiting[0] is r2 and s.waiting[1] is r3
        assert r2.cached_rows == 0

    def test_growth_clamps_at_table_width(self):
        alloc, s = _sched(num_blocks=32, max_seqs=2, bs=16, quantum=8, mb=3)
        r = s.submit(np.arange(40), 16)
        s.schedule()
        r.cached_rows = 47                  # target 55 -> 4 blocks > mb=3
        s.schedule()
        assert len(r.block_ids) == 3        # clamped, no table overflow


# ---------------------------------------------------------------------------
# Paged vs contiguous decode: bit-for-bit
# ---------------------------------------------------------------------------

def _paged_vs_contiguous(kv_bits, dtype, steps=24):
    cfg = _cfg(dtype=dtype, kv_cache_bits=kv_bits)
    model = make_model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    B, P, bs, MB = 2, 32, 16, 6            # gathered width == max_len == 96
    ids = rng.integers(0, cfg.vocab_size, (B, P)).astype(np.int32)

    cache = model.init_cache(B, MB * bs, dtype=dtype)
    lg_c, cache = model.prefill(params, jnp.asarray(ids), cache)

    pools = model.init_paged_cache(num_blocks=B * MB + 1, block_size=bs,
                                   dtype=dtype)
    tabs = np.zeros((B, MB), np.int32)
    nxt_blk = 1
    lg_rows = []
    for s in range(B):
        row = list(range(nxt_blk, nxt_blk + MB))
        nxt_blk += MB
        tabs[s] = row
        lgp, pools = model.prefill_paged(params, jnp.asarray(ids[s:s + 1]),
                                         pools,
                                         jnp.asarray(row[:P // bs],
                                                     jnp.int32), length=P)
        lg_rows.append(lgp)
    lg_p = jnp.concatenate(lg_rows, 0)
    np.testing.assert_array_equal(np.asarray(lg_c), np.asarray(lg_p))

    tok = jnp.argmax(lg_c, -1).astype(jnp.int32)
    tok_p = jnp.argmax(lg_p, -1).astype(jnp.int32)
    tabs_d = jnp.asarray(tabs)
    lens = jnp.asarray([P] * B, jnp.int32)
    dsc = jax.jit(lambda p, t, c: model.decode_step(p, t, c))
    dsp = jax.jit(lambda p, t, pl, tb, ln: model.decode_step_paged(
        p, t, pl, tb, ln, backend="xla"))
    for i in range(steps):
        lc, cache = dsc(params, tok, cache)
        lp, pools = dsp(params, tok_p, pools, tabs_d, lens)
        lens = lens + 1
        # bit-for-bit: the paged read is the SAME einsum chain on a
        # gathered view of identical values (junk masked to exact zeros)
        np.testing.assert_array_equal(np.asarray(lc), np.asarray(lp),
                                      err_msg=f"step {i}")
        tok = jnp.argmax(lc, -1).astype(jnp.int32)
        tok_p = jnp.argmax(lp, -1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(tok), np.asarray(tok_p))


def test_paged_matches_contiguous_bf16():
    """>= 20 greedy decode steps, bf16 cache: logits and tokens exactly
    equal between the paged pool and the contiguous ring buffer."""
    _paged_vs_contiguous(0, jnp.bfloat16)


@pytest.mark.slow
def test_paged_matches_contiguous_int8_kv():
    """Same contract through the int8-quantized pool (scales gathered and
    fused into the score scaling — identical math to the int8 ring)."""
    _paged_vs_contiguous(8, jnp.bfloat16)


def test_paged_kernel_agrees_with_xla_gather():
    """_paged_attention backend parity on mixed lengths (interpret-mode
    Pallas on CPU): the measured backend choice must never change
    results."""
    from deepspeed_tpu.models.transformer import _paged_attention
    cfg = _cfg()
    S, NB, MB, nkv, nq, bs, D = 3, 10, 3, 2, 4, 32, 16
    # D=16 < the kernel's TPU-lane sweet spot but interpret mode is exact
    ks = jax.random.split(jax.random.PRNGKey(5), 5)
    q = jax.random.normal(ks[0], (S, 1, nq, D), jnp.float32)
    pk = jax.random.normal(ks[1], (NB, nkv, bs, D), jnp.float32)
    pv = jax.random.normal(ks[2], (NB, nkv, bs, D), jnp.float32)
    kr = jax.random.normal(ks[3], (S, nkv, 1, D), jnp.float32)
    vr = jax.random.normal(ks[4], (S, nkv, 1, D), jnp.float32)
    tabs = jnp.asarray(
        np.random.default_rng(0).permutation(np.arange(1, 10))[:S * MB]
        .reshape(S, MB), jnp.int32)
    lens = jnp.asarray([0, 17, 96], jnp.int32)
    o_x = _paged_attention(q, pk, pv, tabs, lens, cfg, kv_row=(kr, vr),
                           backend="xla")
    o_p = _paged_attention(q, pk, pv, tabs, lens, cfg, kv_row=(kr, vr),
                           backend="pallas")
    np.testing.assert_allclose(np.asarray(o_x), np.asarray(o_p),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Engine end-to-end
# ---------------------------------------------------------------------------

def _serving(model=None, params=None, **serving):
    model = model or make_model(_cfg())
    defaults = dict(max_seqs=2, block_size=16, max_model_len=128,
                    decode_quantum=4, prompt_bucket=16)
    defaults.update(serving)
    return deepspeed_tpu.init_serving(model, config={}, serving=defaults,
                                      dtype=jnp.float32, params=params)


def test_serving_matches_oneshot_generate():
    """Two concurrent variable-length requests through the serving engine
    produce exactly the one-shot greedy generate() outputs."""
    model = make_model(_cfg())
    srv = _serving(model)
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, 128, size=(7,)).astype(np.int32), 9),
            (rng.integers(0, 128, size=(21,)).astype(np.int32), 6)]
    outs = srv.run(reqs)
    assert srv.scheduler.done
    eng = deepspeed_tpu.init_inference(
        model, config={"kv_cache_bits": 0}, dtype=jnp.float32,
        params=jax.device_get(srv.engine.params))
    for i, (p, n) in enumerate(reqs):
        one = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        np.testing.assert_array_equal(outs[i], one)
    st = srv.stats()
    assert st["completed"] == 2 and st["generated_tokens"] == 15
    assert st["p50_ttft_ms"] > 0 and st["tok_per_sec"] > 0


@pytest.mark.slow
def test_serving_multitenant_queue_and_exhaustion():
    """More requests than slots + a pool sized BELOW full residency: the
    scheduler queues and (under growth pressure) preempts, every request
    still completes with the exact one-shot output, and the pool never
    OOMs. Also pins continuous batching actually interleaving: with 2
    slots and 5 requests the engine must run multiple rounds."""
    model = make_model(_cfg())
    # 9 usable blocks < 2 slots x 8 full-residency blocks
    srv = _serving(model, num_blocks=10)
    rng = np.random.default_rng(3)
    reqs = [(rng.integers(0, 128, size=(n,)).astype(np.int32), k)
            for n, k in ((30, 40), (25, 30), (5, 12), (40, 20), (17, 8))]
    outs = srv.run(reqs)
    assert len(outs) == 5 and srv.allocator.used_blocks == 0
    eng = deepspeed_tpu.init_inference(
        model, config={"kv_cache_bits": 0}, dtype=jnp.float32,
        params=jax.device_get(srv.engine.params))
    for i, (p, n) in enumerate(reqs):
        one = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        np.testing.assert_array_equal(outs[i], one,
                                      err_msg=f"request {i} diverged")


@pytest.mark.slow
def test_serving_int8_kv_pool():
    """Quantized serving: int8 KV blocks end to end (the int8 pool rides
    the same scheduler/tables; dequant is fused into the read)."""
    model = make_model(_cfg())
    # kv_cache_bits=8 flows through the InferenceConfig surface
    srv = deepspeed_tpu.init_serving(
        model, config={"kv_cache_bits": 8}, serving=dict(
            max_seqs=2, block_size=16, max_model_len=128,
            decode_quantum=4, prompt_bucket=16), dtype=jnp.float32)
    assert srv.model.config.kv_cache_bits == 8
    assert srv.pools["k"].dtype == jnp.int8
    rng = np.random.default_rng(5)
    reqs = [(rng.integers(0, 128, size=(12,)).astype(np.int32), 8),
            (rng.integers(0, 128, size=(33,)).astype(np.int32), 8)]
    outs = srv.run(reqs)
    # int8 parity bar: same as the contiguous int8 cache — compare against
    # the one-shot engine with the SAME int8 cache (bit-for-bit paged ==
    # contiguous is pinned in test_paged_matches_contiguous_int8_kv)
    eng = deepspeed_tpu.init_inference(
        model, config={"kv_cache_bits": 8}, dtype=jnp.float32,
        params=jax.device_get(srv.engine.params))
    for i, (p, n) in enumerate(reqs):
        one = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        # windowed-read staging differs from the paged read here, so the
        # bar is greedy-token agreement on the first tokens + near-total
        got = outs[i]
        assert (got[:p.size + 4] == one[:p.size + 4]).all(), (got, one)
        assert (got == one).mean() > 0.9


def test_backend_selection_event_and_reason():
    """The backend choice short-circuits with a recorded reason and lands
    in the telemetry event stream. Capability gates take precedence over
    everything (a FORCED pallas that the decode step would silently
    downgrade must be refused with the why), then the non-TPU check."""
    from deepspeed_tpu.robustness import events
    events.clear()
    srv = _serving()                      # head_dim 16: kernel-ineligible
    assert srv.decode_backend == "xla"
    assert srv.backend_bench["reason"] == "head_dim 16 < 64"
    evs = events.history("decode_backend_selected")
    assert evs and evs[-1]["backend"] == "xla"
    # forced pallas on an ineligible config: refused, reason says why
    srv2 = _serving(model=make_model(_cfg()), decode_backend="pallas")
    assert srv2.decode_backend == "xla"
    assert "pallas unavailable" in srv2.backend_bench["reason"]
    # kernel-eligible shape on CPU: the non-TPU short-circuit
    big = make_model(_cfg(hidden_size=256))   # head_dim 64
    srv3 = _serving(model=big)
    assert srv3.backend_bench["reason"] == "non-TPU backend"


def test_kv_cache_bits_default_is_context_aware():
    """The r5 regression fix: short-context engines keep the compute-dtype
    cache (decode there is op-latency bound; blanket int8 cost the ctx-256
    rung 2.6%), long-context engines default to int8."""
    model = make_model(_cfg())
    short = deepspeed_tpu.init_inference(model, config={"max_tokens": 256},
                                         dtype=jnp.float32)
    assert short.model.config.kv_cache_bits == 0
    model2 = make_model(_cfg(max_seq_len=4096))
    long = deepspeed_tpu.init_inference(model2,
                                        config={"max_tokens": 2048},
                                        dtype=jnp.float32)
    assert long.model.config.kv_cache_bits == 8


def test_init_serving_respects_explicit_max_tokens():
    """The serving-cap default must not override an explicit user
    max_tokens (which drives the context-aware int8-KV default)."""
    model = make_model(_cfg(max_seq_len=4096))
    srv = deepspeed_tpu.init_serving(
        model, config={"max_tokens": 256},
        serving=dict(max_seqs=2, block_size=16, max_model_len=2048),
        dtype=jnp.float32)
    assert srv.engine.config.max_tokens == 256
    assert srv.model.config.kv_cache_bits == 0    # user's short-ctx intent
    srv2 = deepspeed_tpu.init_serving(
        model, serving=dict(max_seqs=2, block_size=16, max_model_len=2048),
        dtype=jnp.float32)
    assert srv2.engine.config.max_tokens == 2048  # default: serving cap
    assert srv2.model.config.kv_cache_bits == 8


def test_init_serving_clamps_max_tokens_to_model_cap():
    """Over-asking max_model_len on a short-context model must not flip
    the engine's int8-KV default: max_tokens clamps to the model cap the
    same way the serving cap does (the r5 regression class)."""
    model = make_model(_cfg())                     # max_seq_len 256
    srv = deepspeed_tpu.init_serving(model, serving=dict(
        max_seqs=2, block_size=16, max_model_len=2048), dtype=jnp.float32)
    assert srv.max_model_len == 256
    assert srv.engine.config.max_tokens == 256
    assert srv.model.config.kv_cache_bits == 0


def test_measure_paged_backends_returns_timings():
    """The shared micro-bench recipe (engine init + bench evidence) runs
    both backends and returns positive timings (interpret-mode Pallas on
    CPU — tiny shapes)."""
    from deepspeed_tpu.inference.serving import measure_paged_backends
    cfg = _cfg()
    nkv, hd = cfg.kv_heads, cfg.dim_per_head
    kp = jnp.zeros((5, nkv, 8, hd), jnp.float32)
    xla_ms, pallas_ms = measure_paged_backends(
        cfg, kp, kp, max_seqs=2, MB=2, block_size=8, num_blocks=5,
        dtype=jnp.float32, iters=1)
    assert xla_ms > 0 and pallas_ms > 0


def test_add_request_validates_context_cap():
    srv = _serving()
    with pytest.raises(ValueError, match="max_model_len"):
        srv.add_request(np.arange(120, dtype=np.int32), 64)


def test_pool_must_fit_one_sequence():
    with pytest.raises(ValueError, match="num_blocks"):
        _serving(num_blocks=4)   # max_model_len 128 / bs 16 needs 8 + trash


def test_paged_cache_leak_corpus_entry():
    """The seeded defect must fire `memory-peak`; the correctly-freed twin
    stays under the identical budget (regression floor for modeling the
    block pool in MemoryLint)."""
    from deepspeed_tpu.analysis.analyzers import AnalysisSettings
    from deepspeed_tpu.analysis.corpus import (PAGED_LEAK_BUDGET,
                                               _paged_decode_program,
                                               run_corpus)
    from deepspeed_tpu.analysis.lint import analyze_programs
    from deepspeed_tpu.analysis.corpus import _FakePlan, _stage0_config
    rep = run_corpus("paged-cache-leak")
    assert not rep.ok
    assert any(f.rule == "memory-peak" for f in rep.findings)
    art = _paged_decode_program(num_blocks=33)
    rep2 = analyze_programs(
        [art], _stage0_config(), _FakePlan(),
        settings=AnalysisSettings(max_hbm_bytes=PAGED_LEAK_BUDGET))
    assert rep2.ok, [f.rule for f in rep2.findings]


# ---------------------------------------------------------------------------
# Reliability tier (ISSUE 10): typed allocator errors, aging, watermarks,
# deadlines, fault recovery, drain/resume
# ---------------------------------------------------------------------------

from deepspeed_tpu.inference.kv_cache import InvalidBlock  # noqa: E402
from deepspeed_tpu.inference.scheduler import AdmissionRejected  # noqa: E402
from deepspeed_tpu.robustness import events as rb_events  # noqa: E402
from deepspeed_tpu.robustness import faults as rb_faults  # noqa: E402
from deepspeed_tpu.robustness.faults import (FaultInjector,  # noqa: E402
                                             FaultSchedule)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    """Reliability tests install process-global injectors; never leak one
    into a neighboring test."""
    rb_faults.clear()
    yield
    rb_faults.clear()


class TestInvalidBlock:
    def test_out_of_range_free_raises_typed_with_owner(self):
        """Both directions of the satellite: an out-of-range id (high OR
        negative — the negative case previously WRAPPED into another
        block's held bit via Python list indexing) raises InvalidBlock
        naming the block and owning sequence; a valid free still works."""
        a = BlockAllocator(8)
        ids = a.alloc(3)
        with pytest.raises(InvalidBlock, match=r"block id 99.*sequence 7"):
            a.free([99], owner=7)
        with pytest.raises(InvalidBlock, match=r"block id -1"):
            a.free([-1])
        # the failed frees changed nothing: the held blocks free cleanly
        a.free(ids, owner=7)
        assert a.free_blocks == 7
        with pytest.raises(ValueError, match="double free"):
            a.free([ids[0]])

    def test_invalid_block_is_a_value_error(self):
        # callers catching the pre-typed ValueError keep working
        assert issubclass(InvalidBlock, ValueError)

    def test_reserve_squeezes_visible_pool_only(self):
        a = BlockAllocator(8)
        a.set_reserve(5)
        assert a.free_blocks == 2
        assert not a.can_alloc(3)
        got = a.alloc(2)
        with pytest.raises(BlockPoolExhausted, match="squeezed"):
            a.alloc(1)
        a.set_reserve(0)
        assert a.free_blocks == 5
        a.free(got)


class TestSchedulerAntiStarvation:
    def test_resumed_tenant_is_not_revictimized(self):
        """The satellite pin, 2-slot pool: when growth pressure returns
        and the only co-tenant is a request that was ALREADY preempted
        once, the victim ROTATES — the grower yields — instead of
        re-preempting the same resumed request. The pre-aging
        ``running.pop()`` picked the resumed request every time (it was
        always the newest list entry): the livelock this pins against."""
        alloc, s = _sched(num_blocks=7, max_seqs=2, bs=16, quantum=4, mb=8)
        r1 = s.submit(np.arange(30), 64)       # 3 blocks each
        r2 = s.submit(np.arange(30), 64)
        assert len(s.schedule()["admitted"]) == 2
        assert (r1.admission_seq, r2.admission_seq) == (0, 1)
        assert alloc.free_blocks == 0
        # r2 stands in for a request that was preempted once and resumed:
        # same slot, same blocks, but it carries the aging bonus
        r2.preemptions = 1
        r1.cached_rows = 46                    # r1 needs a 4th block
        r1.generated = list(range(16))
        out = s.schedule()
        # effective seq: r1 = 0, r2 = 1 - AGING_BONUS*1 = -1 -> the GROWER
        # rotates out; r2 keeps its slot and makes progress
        assert out["preempted"] == [r1]
        assert r2.state == "running" and r2.preemptions == 1
        assert r1.state == "waiting" and r1.preemptions == 1
        # r1's generated tokens survive for its re-prefill resume
        assert r1.generated == list(range(16))

    def test_two_slot_adversarial_no_repeat_victim(self):
        """End-to-end adversarial pattern: 2 slots, a 5-block pool, a new
        arrival every round, every tenant growing a quantum per round and
        finishing at 24 tokens. Sustained churn must never preempt the
        same request twice in a row while another tenant was running, and
        the queue keeps draining (no livelock: requests finish)."""
        alloc, s = _sched(num_blocks=5, max_seqs=2, bs=16, quantum=8,
                          mb=8)
        reqs = [s.submit(np.arange(16), 24) for _ in range(2)]
        victims = []          # (rid, tenants alive at preemption)
        done = 0
        for rnd in range(16):
            out = s.schedule()
            victims += [(r.rid, len(s.running) + len(out["preempted"]))
                        for r in out["preempted"]]
            for r in list(s.running):  # a quantum of growth per round
                r.generated.extend([1] * 8)
                r.cached_rows = len(r.prompt) + len(r.generated)
                if len(r.generated) >= r.max_new_tokens:
                    s.finish(r)
                    done += 1
            reqs.append(s.submit(np.arange(16), 24))   # adversarial stream
        assert len(victims) >= 3, victims
        repeats = [(a, b) for a, b in zip(victims, victims[1:])
                   if a[0] == b[0] and b[1] >= 2]
        assert not repeats, f"victim repeated with tenants alive: {victims}"
        assert done >= 5          # the pool kept serving through the churn
        # every preempted request either finished or is still en route —
        # none is starved with multiple preemptions
        for rid, _ in victims:
            req = next(r for r in reqs if r.rid == rid)
            assert req.preemptions <= 2, (rid, req.preemptions)


class TestAdmissionWatermarks:
    def test_queue_watermark_sheds_typed_and_counts(self):
        rb_events.clear()
        srv = _serving(max_queue=1)
        srv.add_request(np.arange(4, dtype=np.int32), 4)
        with pytest.raises(AdmissionRejected, match="queue_full"):
            srv.add_request(np.arange(4, dtype=np.int32), 4)
        assert srv.stats()["shed"] == 1.0
        evs = rb_events.history("request_shed")
        assert evs and evs[-1]["reason"] == "queue_full"
        # the accepted request still completes
        while not srv.scheduler.done:
            srv.step()
        assert srv.stats()["completed"] == 1.0

    def test_pool_watermark_sheds_under_pressure(self):
        srv = _serving(pool_watermark=0.05)
        srv.add_request(np.arange(8, dtype=np.int32), 32)
        srv.step()                       # admitted: pool now holds blocks
        assert srv.allocator.used_fraction > 0.05
        with pytest.raises(AdmissionRejected, match="pool_pressure"):
            srv.add_request(np.arange(8, dtype=np.int32), 4)

    def test_unbounded_queue_corpus_both_directions(self):
        """The seeded defect fires `queue-growth`; the watermarked twin
        sheds (typed) and passes — both runnable from the CLI too
        (analysis.lint --corpus / analysis.serving_lint --max-queue)."""
        from deepspeed_tpu.analysis.corpus import run_corpus
        from deepspeed_tpu.analysis.serving_lint import audit_admission
        rep = run_corpus("serving-unbounded-queue")
        assert not rep.ok
        assert any(f.rule == "queue-growth" for f in rep.findings)
        assert rep.meta["shed"] == 0
        twin = audit_admission(max_queue=8)
        assert twin.ok, [f.rule for f in twin.findings]
        assert twin.meta["shed"] > 0                 # typed, not silent
        assert max(twin.meta["queue_depths"]) <= 8   # bounded


class TestDeadlines:
    def test_total_deadline_cancels_mid_decode_and_frees_blocks(self):
        rb_events.clear()
        srv = _serving()
        rid = srv.add_request(np.arange(9, dtype=np.int32), 64)
        srv.step()                       # admits + generates a quantum
        held = srv.allocator.used_blocks
        assert held > 0
        # the budget expires while the request is mid-decode (set after
        # the first round so compile wall-time can't race the clock)
        srv._requests[rid].deadline_ms = 1e-3
        srv.step()                       # boundary sweep: past deadline
        req = srv._requests[rid]
        assert req.state == "cancelled"
        assert req.cancel_reason == "total_deadline"
        assert srv.allocator.used_blocks == 0    # blocks returned mid-decode
        assert srv.scheduler.done
        st = srv.stats()
        assert st["deadline_misses"] == 1.0 and st["cancelled"] == 1.0
        assert st["completed"] == 0.0
        # partial output stays readable; the miss is a structured event
        assert len(srv.cancelled) == 1 and len(req.output) >= 9
        ev = rb_events.history("deadline_miss")[-1]
        assert ev["rid"] == rid and ev["kind"] == "total"

    def test_ttft_deadline_sheds_queued_request(self):
        srv = _serving(max_seqs=1)
        # slot taken by a long request; the queued one can never make TTFT
        first = srv.add_request(np.arange(5, dtype=np.int32), 24)
        queued = srv.add_request(np.arange(5, dtype=np.int32), 8,
                                 ttft_deadline_ms=1e-3)
        srv.step()              # round 1: `first` admitted and decoding
        srv.step()              # boundary sweep sheds the queued request
        q = srv._requests[queued]
        assert q.state == "cancelled" and q.cancel_reason == "ttft_deadline"
        assert not q.generated
        # `first` got its first token in round 1: TTFT no longer applies
        f = srv._requests[first]
        assert f.first_token_t is not None
        assert f.state in ("running", "finished")
        while not srv.scheduler.done:
            srv.step()
        assert f.state == "finished"
        st = srv.stats()
        assert st["deadline_misses"] == 1.0 and st["completed"] == 1.0


class TestFaultRecovery:
    def test_dispatch_fault_recovers_bit_identical(self):
        """An injected failed dispatch mid-serve: the engine preempts all,
        rebuilds the pool, re-prefills from host cursors — outputs exactly
        equal the fault-free run, recovery evented."""
        model = make_model(_cfg())
        params = model.init(jax.random.PRNGKey(0))
        import jax as _jax
        rng = np.random.default_rng(2)
        reqs = [(rng.integers(0, 128, size=(n,)).astype(np.int32), k)
                for n, k in ((7, 16), (21, 12))]

        def fresh():
            return _serving(model=model,
                            params=_jax.device_get(params))

        base = fresh().run(list(reqs))
        rb_events.clear()
        inj = rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "decode_dispatch", "at": 1},
            {"kind": "pool_exhaust", "at": 3},
        ], seed=0)))
        srv = fresh()
        outs = srv.run(list(reqs))
        assert {f["kind"] for f in inj.fired} == {"decode_dispatch",
                                                  "pool_exhaust"}
        st = srv.stats()
        assert st["recoveries"] >= 1 and st["recovery_ms"] > 0
        assert rb_events.history("serving_recovered")
        for i in base:
            np.testing.assert_array_equal(base[i], outs[i],
                                          err_msg=f"request {i}")

    def test_round_failure_exhausts_retries_and_raises(self):
        """A deterministic fault (times > retries) must surface, not spin:
        the typed failure names the retry budget."""
        rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "decode_dispatch", "at": 0, "times": 99},
        ], seed=0)))
        srv = _serving(round_retries=1)
        srv.add_request(np.arange(5, dtype=np.int32), 4)
        with pytest.raises(RuntimeError, match="recovery retries"):
            srv.step()
        assert srv.stats()["recoveries"] == 2.0   # 1 try + 1 retry


class TestDrainResume:
    def test_drain_resume_bit_identical(self, tmp_path):
        """SIGTERM contract minus the signal: drain() checkpoints block
        tables + host cursors + generated tokens through the integrity
        chain; a FRESH engine resumes them and the merged outputs equal
        the uninterrupted run byte for byte."""
        import jax as _jax
        model = make_model(_cfg())
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(4)
        reqs = [(rng.integers(0, 128, size=(n,)).astype(np.int32), k)
                for n, k in ((7, 12), (21, 8), (12, 10))]

        def fresh():
            return _serving(model=model, params=_jax.device_get(params))

        base = fresh().run(list(reqs))

        rb_events.clear()
        srv = fresh()
        for p, k in reqs:
            srv.add_request(p, k)
        srv.step()                        # partial progress
        tag_dir = srv.drain(str(tmp_path))
        from deepspeed_tpu.robustness import integrity
        ok, reason = integrity.validate_tag(tag_dir)
        assert ok, reason                 # manifest + COMMITTED, verified
        with pytest.raises(AdmissionRejected, match="draining"):
            srv.add_request(np.arange(3, dtype=np.int32), 4)

        srv2 = fresh()
        rids = srv2.resume(str(tmp_path))
        assert rids                       # something was in flight
        outs = {}
        while not srv2.scheduler.done:
            for r in srv2.step():
                outs[r.rid] = r.output
        for r in srv._finished:           # finished before the drain
            outs.setdefault(r.rid, r.output)
        assert set(outs) == set(base)
        for i in base:
            np.testing.assert_array_equal(base[i], outs[i],
                                          err_msg=f"request {i}")
        assert rb_events.history("serving_drained")
        assert rb_events.history("serving_resumed")

    def test_resume_refuses_torn_drain(self, tmp_path):
        """A drain without its COMMITTED marker (crash mid-drain) must be
        skipped by tag resolution, not half-loaded."""
        srv = _serving()
        srv.add_request(np.arange(5, dtype=np.int32), 8)
        tag_dir = srv.drain(str(tmp_path))
        import os
        os.remove(os.path.join(tag_dir, "COMMITTED"))
        srv2 = _serving()
        with pytest.raises(FileNotFoundError, match="integrity-valid"):
            srv2.resume(str(tmp_path))

    def test_resume_refuses_smaller_engine(self, tmp_path):
        """Resuming into an engine with a smaller context cap must refuse
        loudly — past the block-table width the growth clamp would
        silently corrupt the continuation. Cross-replica (ISSUE 11): the
        refusal is TYPED (ResumeIncompatible) and fires on the drained
        engine's recorded geometry, so a whole-drain resume onto a
        smaller pool refuses even before any individual request is
        checked."""
        from deepspeed_tpu.inference.serving import ResumeIncompatible
        srv = _serving()                          # max_model_len 128
        srv.add_request(np.arange(60, dtype=np.int32), 60)
        srv.drain(str(tmp_path))
        small = _serving(max_model_len=64)
        with pytest.raises(ResumeIncompatible, match="max_model_len"):
            small.resume(str(tmp_path))
        # the typed error names the block-table geometry both sides
        with pytest.raises(ValueError, match="table width"):
            small.resume(str(tmp_path))

    def test_cross_replica_resume_larger_engine_ok(self, tmp_path):
        """The other direction: a foreign drain resumed onto a LARGER
        engine continues byte-identically (re-prefill determinism across
        engines — the router's failover bar)."""
        import jax as _jax
        model = make_model(_cfg())
        params = model.init(jax.random.PRNGKey(0))
        rng = np.random.default_rng(9)
        reqs = [(rng.integers(0, 128, size=(n,)).astype(np.int32), k)
                for n, k in ((6, 10), (18, 8))]
        small_kw = dict(max_model_len=64, max_seqs=2)
        base = _serving(model=model, params=_jax.device_get(params),
                        **small_kw).run(list(reqs))

        srv = _serving(model=model, params=_jax.device_get(params),
                       **small_kw)
        for p, k in reqs:
            srv.add_request(p, k)
        srv.step()                        # partial progress
        srv.drain(str(tmp_path), source="r-small")
        big = _serving(model=model, params=_jax.device_get(params),
                       max_model_len=128, max_seqs=4)
        rids = big.resume(str(tmp_path))
        assert rids
        outs = {}
        while not big.scheduler.done:
            for r in big.step():
                outs[r.rid] = r.output
        for r in srv._finished:
            outs.setdefault(r.rid, r.output)
        assert set(outs) == set(base)
        for i in base:
            np.testing.assert_array_equal(base[i], outs[i],
                                          err_msg=f"request {i}")

    def test_cross_block_size_resume_compares_tokens_not_widths(
            self, tmp_path):
        """Geometry check is in TOKENS: a strictly larger engine with
        BIGGER blocks (hence a numerically smaller table width) must not
        be falsely refused."""
        srv = _serving()                    # 128 tokens / 16-token blocks
        srv.add_request(np.arange(20, dtype=np.int32), 8)
        srv.drain(str(tmp_path))
        # 256-token cap via 64-token blocks: table width 4 < 8, capacity 2x
        big = _serving(max_model_len=256, block_size=64, prompt_bucket=64)
        assert big.resume(str(tmp_path))    # restores, no refusal

    def test_accept_migration_per_request_check(self, tmp_path):
        """The router's per-request migration path: records that FIT a
        smaller survivor restore fine; the one that can't raises the
        typed ResumeIncompatible (the router then tries the next
        survivor), and the refusal is all-or-nothing for its batch."""
        from deepspeed_tpu.inference.serving import (ResumeIncompatible,
                                                     load_drain_state)
        srv = _serving()                          # max_model_len 128
        srv.add_request(np.arange(8, dtype=np.int32), 8)      # fits 64
        srv.add_request(np.arange(50, dtype=np.int32), 40)    # needs 90
        srv.drain(str(tmp_path), source="r-big")
        state = load_drain_state(str(tmp_path))
        assert state["source"] == "r-big"
        assert state["engine"]["max_model_len"] == 128
        small = _serving(max_model_len=64)
        fits = [r for r in state["requests"] if r["rid"] == 0]
        too_big = [r for r in state["requests"] if r["rid"] == 1]
        assert small.accept_migration(fits, source="r-big") == [0]
        with pytest.raises(ResumeIncompatible, match="max_model_len"):
            small.accept_migration(too_big, source="r-big")
        # all-or-nothing: the failed batch enqueued nothing
        assert small.scheduler.num_waiting == 1
