"""Serving engine: continuous batching + paged KV cache + quantized decode.

Reference behavior being exceeded: SURVEY §6's InferenceEngine serves one
shape-bucketed batch per generate() call; the serving tier admits/evicts at
decode-step boundaries over a shared block pool. The load-bearing contracts
pinned here:

  - paged decode is BIT-FOR-BIT the contiguous ring-buffer decode (same
    einsums on a gathered view — greedy tokens AND logits identical over
    20+ steps, float and int8-KV caches);
  - the scheduler admits FIFO, evicts on finish, preempts newest-first
    under pool pressure, and queues gracefully on exhaustion (never OOM);
  - the Pallas paged kernel and the XLA gather agree (backend is a
    measured choice, logged as a telemetry event, never silently wrong);
  - a leaked block pool is a lint failure (`paged-cache-leak` corpus).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.kv_cache import (BlockAllocator,
                                              BlockPoolExhausted, blocks_for)
from deepspeed_tpu.inference.scheduler import RequestScheduler
from deepspeed_tpu.inference.serving import ServingConfig, ServingEngine
from deepspeed_tpu.models import TransformerConfig, make_model


def _cfg(**overrides):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, max_seq_len=256, position_type="rotary",
                activation="silu_glu", norm_type="rmsnorm",
                tie_embeddings=False, dtype=jnp.float32,
                attention_impl="xla")
    base.update(overrides)
    return TransformerConfig(**base)


# ---------------------------------------------------------------------------
# Block allocator (pure host)
# ---------------------------------------------------------------------------

class TestBlockAllocator:
    def test_block0_reserved_and_lifo_reuse(self):
        a = BlockAllocator(8)
        assert a.free_blocks == 7           # block 0 never in the free list
        got = a.alloc(3)
        assert 0 not in got
        a.free(got)
        assert a.alloc(1) == [got[-1]]      # LIFO: warmest block first

    def test_exhaustion_raises_typed(self):
        a = BlockAllocator(4)
        a.alloc(3)
        assert not a.can_alloc(1)
        with pytest.raises(BlockPoolExhausted):
            a.alloc(1)

    def test_double_free_and_trash_free_raise(self):
        a = BlockAllocator(4)
        ids = a.alloc(2)
        a.free(ids)
        with pytest.raises(ValueError, match="double free"):
            a.free([ids[0]])
        with pytest.raises(ValueError, match="trash"):
            a.free([0])

    def test_blocks_for(self):
        assert blocks_for(0, 16) == 0
        assert blocks_for(1, 16) == 1
        assert blocks_for(16, 16) == 1
        assert blocks_for(17, 16) == 2


# ---------------------------------------------------------------------------
# Scheduler (pure host: admit / evict / preempt ordering)
# ---------------------------------------------------------------------------

def _sched(num_blocks=32, max_seqs=4, bs=16, quantum=4, mb=8):
    alloc = BlockAllocator(num_blocks)
    return alloc, RequestScheduler(
        alloc, max_seqs, bs, quantum,
        prompt_blocks=lambda n: blocks_for(max(n, bs), bs),
        max_blocks_per_seq=mb)


class TestScheduler:
    def test_fifo_admission_order(self):
        _, s = _sched()
        reqs = [s.submit(np.arange(10), 8) for _ in range(3)]
        out = s.schedule()
        assert out["admitted"] == reqs      # arrival order
        assert [r.state for r in reqs] == ["running"] * 3

    def test_slot_limit_queues(self):
        _, s = _sched(max_seqs=2)
        reqs = [s.submit(np.arange(10), 8) for _ in range(3)]
        out = s.schedule()
        assert len(out["admitted"]) == 2
        assert s.num_waiting == 1 and reqs[2].state == "waiting"

    def test_pool_exhaustion_queues_not_raises(self):
        # 9 usable blocks; each request needs ceil((32+4)/16)=3 -> 3 admit
        alloc, s = _sched(num_blocks=10, max_seqs=8)
        reqs = [s.submit(np.arange(32), 8) for _ in range(5)]
        out = s.schedule()
        assert len(out["admitted"]) == 3
        assert s.num_waiting == 2
        assert alloc.free_blocks == 0
        # finishing one frees its blocks and the queue head admits next
        s.finish(reqs[0])
        out = s.schedule()
        assert out["admitted"] == [reqs[3]]

    def test_growth_preempts_newest_first(self):
        # two running, pool exactly covers their prompts; growth pressure
        # must preempt the NEWEST and keep the oldest progressing
        alloc, s = _sched(num_blocks=7, max_seqs=4, bs=16, quantum=4)
        r1 = s.submit(np.arange(30), 64)    # 3 blocks (ctx+quantum=34)
        r2 = s.submit(np.arange(30), 64)
        assert len(s.schedule()["admitted"]) == 2
        assert alloc.free_blocks == 0
        # simulate r1 decoding to the edge of its coverage
        r1.cached_rows = 46                 # needs blocks_for(50)=4 next
        r1.generated = list(range(16))
        out = s.schedule()
        assert out["preempted"] == [r2]
        assert r2.state == "waiting" and r2.preemptions == 1
        assert len(r1.block_ids) == 4       # oldest got its growth
        # the preempted request resumes at the FRONT of the queue with its
        # generated tokens intact (re-prefill recomputes its rows)
        r3 = s.submit(np.arange(8), 8)
        assert s.waiting[0] is r2 and s.waiting[1] is r3
        assert r2.cached_rows == 0

    def test_growth_clamps_at_table_width(self):
        alloc, s = _sched(num_blocks=32, max_seqs=2, bs=16, quantum=8, mb=3)
        r = s.submit(np.arange(40), 16)
        s.schedule()
        r.cached_rows = 47                  # target 55 -> 4 blocks > mb=3
        s.schedule()
        assert len(r.block_ids) == 3        # clamped, no table overflow


# ---------------------------------------------------------------------------
# Paged vs contiguous decode: bit-for-bit
# ---------------------------------------------------------------------------

def _paged_vs_contiguous(kv_bits, dtype, steps=24):
    cfg = _cfg(dtype=dtype, kv_cache_bits=kv_bits)
    model = make_model(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    B, P, bs, MB = 2, 32, 16, 6            # gathered width == max_len == 96
    ids = rng.integers(0, cfg.vocab_size, (B, P)).astype(np.int32)

    cache = model.init_cache(B, MB * bs, dtype=dtype)
    lg_c, cache = model.prefill(params, jnp.asarray(ids), cache)

    pools = model.init_paged_cache(num_blocks=B * MB + 1, block_size=bs,
                                   dtype=dtype)
    tabs = np.zeros((B, MB), np.int32)
    nxt_blk = 1
    lg_rows = []
    for s in range(B):
        row = list(range(nxt_blk, nxt_blk + MB))
        nxt_blk += MB
        tabs[s] = row
        lgp, pools = model.prefill_paged(params, jnp.asarray(ids[s:s + 1]),
                                         pools,
                                         jnp.asarray(row[:P // bs],
                                                     jnp.int32), length=P)
        lg_rows.append(lgp)
    lg_p = jnp.concatenate(lg_rows, 0)
    np.testing.assert_array_equal(np.asarray(lg_c), np.asarray(lg_p))

    tok = jnp.argmax(lg_c, -1).astype(jnp.int32)
    tok_p = jnp.argmax(lg_p, -1).astype(jnp.int32)
    tabs_d = jnp.asarray(tabs)
    lens = jnp.asarray([P] * B, jnp.int32)
    dsc = jax.jit(lambda p, t, c: model.decode_step(p, t, c))
    dsp = jax.jit(lambda p, t, pl, tb, ln: model.decode_step_paged(
        p, t, pl, tb, ln, backend="xla"))
    for i in range(steps):
        lc, cache = dsc(params, tok, cache)
        lp, pools = dsp(params, tok_p, pools, tabs_d, lens)
        lens = lens + 1
        # bit-for-bit: the paged read is the SAME einsum chain on a
        # gathered view of identical values (junk masked to exact zeros)
        np.testing.assert_array_equal(np.asarray(lc), np.asarray(lp),
                                      err_msg=f"step {i}")
        tok = jnp.argmax(lc, -1).astype(jnp.int32)
        tok_p = jnp.argmax(lp, -1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(tok), np.asarray(tok_p))


def test_paged_matches_contiguous_bf16():
    """>= 20 greedy decode steps, bf16 cache: logits and tokens exactly
    equal between the paged pool and the contiguous ring buffer."""
    _paged_vs_contiguous(0, jnp.bfloat16)


@pytest.mark.slow
def test_paged_matches_contiguous_int8_kv():
    """Same contract through the int8-quantized pool (scales gathered and
    fused into the score scaling — identical math to the int8 ring)."""
    _paged_vs_contiguous(8, jnp.bfloat16)


def test_paged_kernel_agrees_with_xla_gather():
    """_paged_attention backend parity on mixed lengths (interpret-mode
    Pallas on CPU): the measured backend choice must never change
    results."""
    from deepspeed_tpu.models.transformer import _paged_attention
    cfg = _cfg()
    S, NB, MB, nkv, nq, bs, D = 3, 10, 3, 2, 4, 32, 16
    # D=16 < the kernel's TPU-lane sweet spot but interpret mode is exact
    ks = jax.random.split(jax.random.PRNGKey(5), 5)
    q = jax.random.normal(ks[0], (S, 1, nq, D), jnp.float32)
    pk = jax.random.normal(ks[1], (NB, nkv, bs, D), jnp.float32)
    pv = jax.random.normal(ks[2], (NB, nkv, bs, D), jnp.float32)
    kr = jax.random.normal(ks[3], (S, nkv, 1, D), jnp.float32)
    vr = jax.random.normal(ks[4], (S, nkv, 1, D), jnp.float32)
    tabs = jnp.asarray(
        np.random.default_rng(0).permutation(np.arange(1, 10))[:S * MB]
        .reshape(S, MB), jnp.int32)
    lens = jnp.asarray([0, 17, 96], jnp.int32)
    o_x = _paged_attention(q, pk, pv, tabs, lens, cfg, kv_row=(kr, vr),
                           backend="xla")
    o_p = _paged_attention(q, pk, pv, tabs, lens, cfg, kv_row=(kr, vr),
                           backend="pallas")
    np.testing.assert_allclose(np.asarray(o_x), np.asarray(o_p),
                               rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Engine end-to-end
# ---------------------------------------------------------------------------

def _serving(model=None, params=None, **serving):
    model = model or make_model(_cfg())
    defaults = dict(max_seqs=2, block_size=16, max_model_len=128,
                    decode_quantum=4, prompt_bucket=16)
    defaults.update(serving)
    return deepspeed_tpu.init_serving(model, config={}, serving=defaults,
                                      dtype=jnp.float32, params=params)


def test_serving_matches_oneshot_generate():
    """Two concurrent variable-length requests through the serving engine
    produce exactly the one-shot greedy generate() outputs."""
    model = make_model(_cfg())
    srv = _serving(model)
    rng = np.random.default_rng(0)
    reqs = [(rng.integers(0, 128, size=(7,)).astype(np.int32), 9),
            (rng.integers(0, 128, size=(21,)).astype(np.int32), 6)]
    outs = srv.run(reqs)
    assert srv.scheduler.done
    eng = deepspeed_tpu.init_inference(
        model, config={"kv_cache_bits": 0}, dtype=jnp.float32,
        params=jax.device_get(srv.engine.params))
    for i, (p, n) in enumerate(reqs):
        one = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        np.testing.assert_array_equal(outs[i], one)
    st = srv.stats()
    assert st["completed"] == 2 and st["generated_tokens"] == 15
    assert st["p50_ttft_ms"] > 0 and st["tok_per_sec"] > 0


@pytest.mark.slow
def test_serving_multitenant_queue_and_exhaustion():
    """More requests than slots + a pool sized BELOW full residency: the
    scheduler queues and (under growth pressure) preempts, every request
    still completes with the exact one-shot output, and the pool never
    OOMs. Also pins continuous batching actually interleaving: with 2
    slots and 5 requests the engine must run multiple rounds."""
    model = make_model(_cfg())
    # 9 usable blocks < 2 slots x 8 full-residency blocks
    srv = _serving(model, num_blocks=10)
    rng = np.random.default_rng(3)
    reqs = [(rng.integers(0, 128, size=(n,)).astype(np.int32), k)
            for n, k in ((30, 40), (25, 30), (5, 12), (40, 20), (17, 8))]
    outs = srv.run(reqs)
    assert len(outs) == 5 and srv.allocator.used_blocks == 0
    eng = deepspeed_tpu.init_inference(
        model, config={"kv_cache_bits": 0}, dtype=jnp.float32,
        params=jax.device_get(srv.engine.params))
    for i, (p, n) in enumerate(reqs):
        one = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        np.testing.assert_array_equal(outs[i], one,
                                      err_msg=f"request {i} diverged")


@pytest.mark.slow
def test_serving_int8_kv_pool():
    """Quantized serving: int8 KV blocks end to end (the int8 pool rides
    the same scheduler/tables; dequant is fused into the read)."""
    model = make_model(_cfg())
    # kv_cache_bits=8 flows through the InferenceConfig surface
    srv = deepspeed_tpu.init_serving(
        model, config={"kv_cache_bits": 8}, serving=dict(
            max_seqs=2, block_size=16, max_model_len=128,
            decode_quantum=4, prompt_bucket=16), dtype=jnp.float32)
    assert srv.model.config.kv_cache_bits == 8
    assert srv.pools["k"].dtype == jnp.int8
    rng = np.random.default_rng(5)
    reqs = [(rng.integers(0, 128, size=(12,)).astype(np.int32), 8),
            (rng.integers(0, 128, size=(33,)).astype(np.int32), 8)]
    outs = srv.run(reqs)
    # int8 parity bar: same as the contiguous int8 cache — compare against
    # the one-shot engine with the SAME int8 cache (bit-for-bit paged ==
    # contiguous is pinned in test_paged_matches_contiguous_int8_kv)
    eng = deepspeed_tpu.init_inference(
        model, config={"kv_cache_bits": 8}, dtype=jnp.float32,
        params=jax.device_get(srv.engine.params))
    for i, (p, n) in enumerate(reqs):
        one = np.asarray(eng.generate(p[None], max_new_tokens=n))[0]
        # windowed-read staging differs from the paged read here, so the
        # bar is greedy-token agreement on the first tokens + near-total
        got = outs[i]
        assert (got[:p.size + 4] == one[:p.size + 4]).all(), (got, one)
        assert (got == one).mean() > 0.9


def test_backend_selection_event_and_reason():
    """The backend choice short-circuits with a recorded reason and lands
    in the telemetry event stream. Capability gates take precedence over
    everything (a FORCED pallas that the decode step would silently
    downgrade must be refused with the why), then the non-TPU check."""
    from deepspeed_tpu.robustness import events
    events.clear()
    srv = _serving()                      # head_dim 16: kernel-ineligible
    assert srv.decode_backend == "xla"
    assert srv.backend_bench["reason"] == "head_dim 16 < 64"
    evs = events.history("decode_backend_selected")
    assert evs and evs[-1]["backend"] == "xla"
    # forced pallas on an ineligible config: refused, reason says why
    srv2 = _serving(model=make_model(_cfg()), decode_backend="pallas")
    assert srv2.decode_backend == "xla"
    assert "pallas unavailable" in srv2.backend_bench["reason"]
    # kernel-eligible shape on CPU: the non-TPU short-circuit
    big = make_model(_cfg(hidden_size=256))   # head_dim 64
    srv3 = _serving(model=big)
    assert srv3.backend_bench["reason"] == "non-TPU backend"


def test_kv_cache_bits_default_is_context_aware():
    """The r5 regression fix: short-context engines keep the compute-dtype
    cache (decode there is op-latency bound; blanket int8 cost the ctx-256
    rung 2.6%), long-context engines default to int8."""
    model = make_model(_cfg())
    short = deepspeed_tpu.init_inference(model, config={"max_tokens": 256},
                                         dtype=jnp.float32)
    assert short.model.config.kv_cache_bits == 0
    model2 = make_model(_cfg(max_seq_len=4096))
    long = deepspeed_tpu.init_inference(model2,
                                        config={"max_tokens": 2048},
                                        dtype=jnp.float32)
    assert long.model.config.kv_cache_bits == 8


def test_init_serving_respects_explicit_max_tokens():
    """The serving-cap default must not override an explicit user
    max_tokens (which drives the context-aware int8-KV default)."""
    model = make_model(_cfg(max_seq_len=4096))
    srv = deepspeed_tpu.init_serving(
        model, config={"max_tokens": 256},
        serving=dict(max_seqs=2, block_size=16, max_model_len=2048),
        dtype=jnp.float32)
    assert srv.engine.config.max_tokens == 256
    assert srv.model.config.kv_cache_bits == 0    # user's short-ctx intent
    srv2 = deepspeed_tpu.init_serving(
        model, serving=dict(max_seqs=2, block_size=16, max_model_len=2048),
        dtype=jnp.float32)
    assert srv2.engine.config.max_tokens == 2048  # default: serving cap
    assert srv2.model.config.kv_cache_bits == 8


def test_init_serving_clamps_max_tokens_to_model_cap():
    """Over-asking max_model_len on a short-context model must not flip
    the engine's int8-KV default: max_tokens clamps to the model cap the
    same way the serving cap does (the r5 regression class)."""
    model = make_model(_cfg())                     # max_seq_len 256
    srv = deepspeed_tpu.init_serving(model, serving=dict(
        max_seqs=2, block_size=16, max_model_len=2048), dtype=jnp.float32)
    assert srv.max_model_len == 256
    assert srv.engine.config.max_tokens == 256
    assert srv.model.config.kv_cache_bits == 0


def test_measure_paged_backends_returns_timings():
    """The shared micro-bench recipe (engine init + bench evidence) runs
    both backends and returns positive timings (interpret-mode Pallas on
    CPU — tiny shapes)."""
    from deepspeed_tpu.inference.serving import measure_paged_backends
    cfg = _cfg()
    nkv, hd = cfg.kv_heads, cfg.dim_per_head
    kp = jnp.zeros((5, nkv, 8, hd), jnp.float32)
    xla_ms, pallas_ms = measure_paged_backends(
        cfg, kp, kp, max_seqs=2, MB=2, block_size=8, num_blocks=5,
        dtype=jnp.float32, iters=1)
    assert xla_ms > 0 and pallas_ms > 0


def test_add_request_validates_context_cap():
    srv = _serving()
    with pytest.raises(ValueError, match="max_model_len"):
        srv.add_request(np.arange(120, dtype=np.int32), 64)


def test_pool_must_fit_one_sequence():
    with pytest.raises(ValueError, match="num_blocks"):
        _serving(num_blocks=4)   # max_model_len 128 / bs 16 needs 8 + trash


def test_paged_cache_leak_corpus_entry():
    """The seeded defect must fire `memory-peak`; the correctly-freed twin
    stays under the identical budget (regression floor for modeling the
    block pool in MemoryLint)."""
    from deepspeed_tpu.analysis.analyzers import AnalysisSettings
    from deepspeed_tpu.analysis.corpus import (PAGED_LEAK_BUDGET,
                                               _paged_decode_program,
                                               run_corpus)
    from deepspeed_tpu.analysis.lint import analyze_programs
    from deepspeed_tpu.analysis.corpus import _FakePlan, _stage0_config
    rep = run_corpus("paged-cache-leak")
    assert not rep.ok
    assert any(f.rule == "memory-peak" for f in rep.findings)
    art = _paged_decode_program(num_blocks=33)
    rep2 = analyze_programs(
        [art], _stage0_config(), _FakePlan(),
        settings=AnalysisSettings(max_hbm_bytes=PAGED_LEAK_BUDGET))
    assert rep2.ok, [f.rule for f in rep2.findings]
