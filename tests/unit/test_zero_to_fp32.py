"""Offline fp32 checkpoint extraction (reference: utils/zero_to_fp32.py:311
— merge shard checkpoints into one fp32 state_dict without an engine)."""

import numpy as np
import pytest

# quick tier: checkpoint-machinery suites re-build engines per test (compile-heavy)
pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.models.transformer import llama_config
from deepspeed_tpu.utils.zero_to_fp32 import (
    convert_zero_checkpoint_to_fp32_state_dict,
    get_fp32_state_dict_from_zero_checkpoint)
from tests.conftest import make_batch


def _model():
    return make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
        max_seq_len=64, dtype=jnp.bfloat16))


class TestZeroToFp32:
    def test_regular_checkpoint_masters(self, tmp_path):
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1}, "steps_per_print": 1000})
        b = make_batch(8, 32, vocab=64)
        for _ in range(2):
            engine.train_batch(b)
        engine.save_checkpoint(str(tmp_path))
        sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
        assert all(v.dtype == np.float32 for v in sd.values())
        # masters match the engine's fp32 master copies exactly
        master = np.asarray(jax.device_get(
            engine.state["opt"]["master"]["tok_embed"]))
        np.testing.assert_array_equal(sd["tok_embed"], master)
        out = convert_zero_checkpoint_to_fp32_state_dict(
            str(tmp_path), str(tmp_path / "fp32"))
        with np.load(out) as z:
            assert "tok_embed" in z.files

    def test_swap_chunk_checkpoint(self, tmp_path):
        """device=cpu offload: masters live in optswap.npz chunks."""
        engine, *_ = deepspeed_tpu.initialize(model=_model(), config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1,
                                  "offload_optimizer": {"device": "cpu"}},
            "steps_per_print": 1000})
        assert engine._swapper is not None
        b = make_batch(8, 32, vocab=64)
        for _ in range(2):
            engine.train_batch(b)
        engine.save_checkpoint(str(tmp_path))
        sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
        # chunk-plane masters track the bf16 params closely
        p = np.asarray(jax.device_get(engine.state["params"]["tok_embed"]),
                       np.float32)
        np.testing.assert_allclose(sd["tok_embed"], p, atol=0.02)

    def test_infinity_checkpoint(self, tmp_path):
        cfg_d = {
            "train_batch_size": 4,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": 3,
                "offload_param": {"device": "cpu"},
                "offload_optimizer": {"device": "cpu"}},
            "steps_per_print": 1000}
        model = make_model(llama_config("tiny", max_seq_len=128,
                                        loss_chunk=64), name="tiny")
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg_d)
        rng = np.random.default_rng(0)
        b = {"input_ids": rng.integers(0, 32000, (4, 128), dtype=np.int32)}
        engine.train_batch(b)
        engine.save_checkpoint(str(tmp_path))
        sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
        ex = engine._infinity_exec
        # stacked layer leaves reconstruct with the true shapes (L=4)
        assert sd["layers/wq"].shape == (4, 256, 256)
        assert "tok_embed" in sd and sd["tok_embed"].dtype == np.float32
        assert all(np.isfinite(v).all() for v in sd.values())
        # master plane round-trips the actual opt chunk for layer 0
        opt0 = np.asarray(jax.device_get(ex.store.read_opt(0)))
        first_leaf_name = sorted(
            k for k in sd if k.startswith("layers/"))[0]
        # leaves are stored in jax.tree.flatten (sorted-key) order
        first = sd[first_leaf_name][0].reshape(-1)
        np.testing.assert_allclose(opt0[0][:first.size], first, atol=1e-6)
        engine._infinity_exec.close()

    def test_missing_latest_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
