"""Checkpoint engine: atomic overwrite, async finalize, directory contract.

Reference: ``runtime/engine.py save_checkpoint:2817 / load_checkpoint:2512``
(tag dirs + `latest` file) and ``runtime/checkpoint_engine/`` (pluggable
engines; Nebula-style async save).
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.checkpointing import (LATEST_FILE,
                                                 OrbaxCheckpointEngine,
                                                 load_checkpoint,
                                                 save_checkpoint)


def tree(val):
    return {"w": jnp.full((4, 4), float(val)), "step": jnp.asarray(val)}


class TestCheckpointContract:
    def test_save_load_roundtrip(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "tag1", tree(1), client_state={"x": 7})
        state, client = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["w"][0, 0])) == 1.0
        assert client["x"] == 7
        assert open(os.path.join(d, LATEST_FILE)).read() == "tag1"

    def test_overwrite_same_tag_is_atomic(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "t", tree(1))
        save_checkpoint(d, "t", tree(2))
        state, _ = load_checkpoint(d, "t", template=tree(0))
        assert float(np.asarray(state["step"])) == 2.0
        # superseded version dirs are garbage-collected: exactly one remains
        versions = [p for p in os.listdir(os.path.join(d, "t"))
                    if p.startswith("state-v")]
        assert len(versions) == 1, versions

    def test_crash_between_write_and_publish_keeps_old(self, tmp_path):
        # simulate a crash mid-save: a second version dir exists but the
        # pointer was never swapped — load must still see the old state
        d = str(tmp_path)
        save_checkpoint(d, "t", tree(1))
        orphan = os.path.join(d, "t", "state-vdeadbeef")
        os.makedirs(orphan)  # partial, never-published write
        state, _ = load_checkpoint(d, "t", template=tree(0))
        assert float(np.asarray(state["step"])) == 1.0

    def test_latest_resolution_picks_newest_tag(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "a", tree(1))
        save_checkpoint(d, "b", tree(2))
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 2.0


class TestAsyncSave:
    def test_async_finalizes_on_wait(self, tmp_path):
        d = str(tmp_path)
        eng = OrbaxCheckpointEngine(async_save=True)
        path = save_checkpoint(d, "t", tree(3), engine=eng)
        eng.wait()
        # after wait: state published via pointer, meta.json + latest written
        assert os.path.exists(os.path.join(path, "state.current"))
        assert os.path.exists(os.path.join(path, "meta.json"))
        assert open(os.path.join(d, LATEST_FILE)).read() == "t"
        state, _ = load_checkpoint(d, template=tree(0), engine=eng)
        assert float(np.asarray(state["step"])) == 3.0

    def test_second_save_finalizes_first(self, tmp_path):
        d = str(tmp_path)
        eng = OrbaxCheckpointEngine(async_save=True)
        save_checkpoint(d, "t1", tree(1), engine=eng)
        save_checkpoint(d, "t2", tree(2), engine=eng)  # must flush t1 first
        assert os.path.exists(os.path.join(d, "t1", "state.current"))
        eng.wait()
        assert os.path.exists(os.path.join(d, "t2", "state.current"))
        s1, _ = load_checkpoint(d, "t1", template=tree(0), engine=eng)
        s2, _ = load_checkpoint(d, "t2", template=tree(0), engine=eng)
        assert float(np.asarray(s1["step"])) == 1.0
        assert float(np.asarray(s2["step"])) == 2.0

    def test_wait_idempotent(self, tmp_path):
        eng = OrbaxCheckpointEngine(async_save=True)
        save_checkpoint(str(tmp_path), "t", tree(1), engine=eng)
        eng.wait()
        eng.wait()  # no pending -> no-op
