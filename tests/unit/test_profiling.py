"""Flops profiler + autotuner tests (reference:
flops_profiler/profiler.py:20, autotuning/autotuner.py:39)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.profiling import FlopsProfiler, get_model_profile
from tests.conftest import make_batch


def _tiny(**kw):
    base = dict(vocab_size=128, hidden_size=64, num_layers=4, num_heads=4,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla",
                tie_embeddings=True, position_type="learned",
                activation="gelu", norm_type="layernorm")
    base.update(kw)
    return TransformerConfig(**base)


class TestFlopsProfiler:
    def test_analytic_matches_6nd(self):
        """Forward flops of the LM must land near the 2*N*D estimate (dense
        matmul-dominated model: 2 flops/param/token forward)."""
        cfg = _tiny()
        model = make_model(cfg)
        params = model.init(jax.random.PRNGKey(0))
        B, S = 4, 64
        ids = jnp.zeros((B, S), jnp.int32)
        prof = get_model_profile(lambda p, i: model.apply(p, i), params, ids,
                                 backend_analysis=False)
        n_matmul_params = (
            cfg.num_layers * (4 * cfg.hidden_size ** 2
                              + 2 * cfg.hidden_size * cfg.ffn_dim)
            + cfg.hidden_size * cfg.vocab_size)  # lm head (tied)
        expect = 2 * n_matmul_params * B * S
        assert 0.8 * expect < prof["flops"] < 1.6 * expect, \
            (prof["flops"], expect)
        assert prof["params"] > 0
        assert "dot_general" in prof["flops_by_primitive"]
        # matmuls must dominate
        assert (prof["flops_by_primitive"]["dot_general"]
                > 0.6 * prof["flops"])

    def test_scan_layers_counted(self):
        """lax.scan over layers multiplies flops by depth: the 8-layer model
        must profile ~2x the 4-layer model."""
        def fwd(cfg):
            model = make_model(cfg)
            p = model.init(jax.random.PRNGKey(0))
            ids = jnp.zeros((2, 64), jnp.int32)
            return get_model_profile(lambda q, i: model.apply(q, i), p, ids,
                                     backend_analysis=False)["flops"]
        f4, f8 = fwd(_tiny(num_layers=4)), fwd(_tiny(num_layers=8))
        assert 1.6 < f8 / f4 < 2.2, (f4, f8)

    def test_pallas_kernel_counts_grid(self):
        """The sparse-attention Pallas kernel's body jaxpr describes ONE
        grid program; the launch runs prod(grid) of them. Counting the body
        once (the r6 coverage gap) reported near-zero attention FLOPs —
        the grid-scaled count must at least cover the listed blocks'
        analytic dot cost."""
        from deepspeed_tpu.ops.sparse_attention import (get_sparsity_config,
                                                        sparse_attention)
        scfg = get_sparsity_config("fixed", block=64, num_local_blocks=2)
        q = jnp.ones((1, 256, 4, 64), jnp.float32)
        prof = get_model_profile(
            lambda q: sparse_attention(q, q, q, scfg, causal=True), q,
            backend_analysis=False)
        # floor: every one of the 4 q-block rows x 4 heads reads >=1 kv
        # block; each block pays a qk and a pv dot of 2*blk*blk*D flops
        blk, D, heads, qblocks = 64, 64, 4, 4
        min_attn = qblocks * heads * 2 * (2 * blk * blk * D)
        assert prof["flops"] >= min_attn, (prof["flops"], min_attn)
        assert "dot_general" in prof["flops_by_primitive"]

    def test_moe_counts_expert_ffn(self):
        """MoE layers must profile MORE than their dense twin (experts +
        dispatch/combine einsums), not zero."""
        def flops(**kw):
            cfg = _tiny(num_layers=2, **kw)
            m = make_model(cfg)
            p = m.init(jax.random.PRNGKey(0))
            ids = jnp.zeros((2, 64), jnp.int32)
            return get_model_profile(lambda q, i: m.apply(q, i), p, ids,
                                     backend_analysis=False)["flops"]
        assert flops(num_experts=4) > 1.2 * flops()

    def test_dense_unrolled_matches_xla_within_10pct(self):
        """Analytic jaxpr walk vs XLA's post-fusion cost analysis on the
        dense UNROLLED path (HloCostAnalysis counts a while/scan body once,
        so the scanned stack is compared unrolled)."""
        cfg = _tiny(num_layers=2, scan_layers=False)
        model = make_model(cfg)
        p = model.init(jax.random.PRNGKey(0))
        ids = jnp.zeros((1, 64), jnp.int32)
        prof = get_model_profile(lambda q, i: model.apply(q, i), p, ids)
        assert "xla_flops" in prof, "backend cost analysis unavailable"
        ratio = prof["flops"] / max(1, prof["xla_flops"])
        assert 0.9 < ratio < 1.1, (prof["flops"], prof["xla_flops"])

    @pytest.mark.slow
    def test_engine_integration_prints_profile(self, devices8, caplog):
        model = make_model(_tiny())
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": False},
            "flops_profiler": {"enabled": True, "profile_step": 2},
            "steps_per_print": 1000})
        b = make_batch(8, 64, vocab=128)
        for _ in range(3):
            engine.train_batch(b)
        prof = getattr(engine, "flops_profile", None)
        assert prof is not None and prof["flops"] > 0
        assert prof["mfu"] > 0 and prof["step_latency_s"] > 0
        assert prof["flops_by_module"]


class TestAutotuner:
    def test_candidates_cover_mesh_space(self, devices8):
        from deepspeed_tpu.autotuning import Autotuner
        model = make_model(_tiny())
        t = Autotuner(model, {"train_batch_size": 16,
                              "autotuning": {"tuner_num_trials": 100}})
        cands = t.candidates()
        assert len(cands) > 4
        meshes = {tuple(sorted(c["mesh"]["axes"].items())) for c in cands}
        assert (("data", 8), ("tensor", 1)) in meshes
        assert (("fsdp", 8), ("tensor", 1)) in meshes
        assert any(dict(m).get("tensor") == 4 for m in meshes)

    @pytest.mark.slow
    def test_autotune_picks_valid_config(self, devices8, tmp_path):
        """End-to-end: autotuning enabled selects a runnable config at least
        as fast as the measured candidates, engine trains with it."""
        model = make_model(_tiny(num_layers=2))
        cfg = {
            "train_batch_size": 16,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": False},
            "autotuning": {"enabled": True, "tuner_num_trials": 3,
                           "tuner_early_stopping": 0,
                           "results_dir": str(tmp_path / "at")},
            "steps_per_print": 1000}
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        assert not engine.config.autotuning.enabled
        b = make_batch(16, 64, vocab=128)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(3)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        import json, os
        results = json.load(open(tmp_path / "at" / "results.json"))
        assert len(results) >= 1
        assert any(r["error"] is None for r in results)

    def test_failed_candidates_score_neg_inf(self, devices8):
        from deepspeed_tpu.autotuning import Autotuner
        model = make_model(_tiny(num_layers=2))
        t = Autotuner(model, {"train_batch_size": 16,
                              "optimizer": {"type": "adamw",
                                            "params": {"lr": 1e-3}},
                              "bf16": {"enabled": False}})
        trial = t.measure({"mesh": {"axes": {"data": 3}},  # 3 does not divide 8
                           "zero_optimization": {"stage": 0},
                           "gradient_accumulation_steps": 1})
        assert trial.error is not None
        assert trial.samples_per_sec == float("-inf")


class TestExperimentScheduler:
    """Multi-host experiment scheduler (reference: autotuning/scheduler.py
    ResourceManager): host-pool partitioning, concurrent disjoint groups,
    result collection from per-experiment dirs."""

    def test_hosts_needed(self):
        from deepspeed_tpu.autotuning.scheduler import hosts_needed
        assert hosts_needed({"mesh": {"axes": {"data": 8}}}, 4) == 2
        assert hosts_needed({"mesh": {"axes": {"data": 2, "tensor": 2}}},
                            4) == 1
        assert hosts_needed({}, 4) == 1

    def test_partitioning_and_concurrency(self, tmp_path):
        """4 hosts, candidates needing 2/2/4: the two 2-host experiments
        must run concurrently on disjoint groups; the 4-host one after."""
        from deepspeed_tpu.autotuning.scheduler import ResourceManager
        import json as _json
        import os as _os
        events = []

        def fake_launch(exp):
            events.append(("launch", exp.exp_id, tuple(exp.hosts)))
            d = _os.path.join(str(tmp_path), f"exp_{exp.exp_id}")
            _os.makedirs(d, exist_ok=True)
            with open(_os.path.join(d, "result.json"), "w") as f:
                _json.dump({"samples_per_sec": 100.0 + exp.exp_id,
                            "step_ms": 10.0}, f)

        rm = ResourceManager(["h0", "h1", "h2", "h3"], chips_per_host=4,
                             results_dir=str(tmp_path), launch=fake_launch,
                             poll_s=0.01)
        cfgs = [{"mesh": {"axes": {"data": 8}}},           # 2 hosts
                {"mesh": {"axes": {"fsdp": 8}}},           # 2 hosts
                {"mesh": {"axes": {"data": 16}}}]          # 4 hosts
        exps = rm.schedule(cfgs)
        # first poll launches BOTH 2-host exps before any completes
        first_two = {e[1] for e in events[:2]}
        assert first_two == {0, 1}
        used = [set(e[2]) for e in events[:2]]
        assert used[0].isdisjoint(used[1])
        assert all(e.status == "done" for e in exps)
        # sorted best-first: exp 2 wrote the highest samples/sec
        assert exps[0].exp_id == 2

    def test_failure_ranks_last(self, tmp_path):
        from deepspeed_tpu.autotuning.scheduler import ResourceManager
        import json as _json
        import os as _os

        def fake_launch(exp):
            d = _os.path.join(str(tmp_path), f"exp_{exp.exp_id}")
            _os.makedirs(d, exist_ok=True)
            if exp.exp_id == 0:
                with open(_os.path.join(d, "result.json"), "w") as f:
                    _json.dump({"error": "OOM"}, f)
            else:
                with open(_os.path.join(d, "result.json"), "w") as f:
                    _json.dump({"samples_per_sec": 5.0}, f)

        rm = ResourceManager(["h0"], results_dir=str(tmp_path),
                             launch=fake_launch, poll_s=0.01)
        exps = rm.schedule([{}, {}])
        assert exps[0].exp_id == 1 and exps[0].status == "done"
        assert exps[1].status == "failed" and exps[1].error == "OOM"

    def test_remote_missing_result_names_shared_fs(self, tmp_path):
        """A remote experiment with no result file must say WHY it probably
        failed: results_dir not on shared storage (the collect path is read
        on the scheduler host)."""
        from deepspeed_tpu.autotuning.scheduler import ResourceManager

        def fake_launch(exp):
            pass  # "remote" run that writes nothing visible locally

        rm = ResourceManager(["far-host-1"], results_dir=str(tmp_path),
                             launch=fake_launch, poll_s=0.01)
        exps = rm.schedule([{}])
        assert exps[0].status == "failed"
        assert "shared" in exps[0].error and "far-host-1" in exps[0].error

    @pytest.mark.slow
    def test_real_local_experiment_subprocess(self, tmp_path):
        """End-to-end: the default launcher runs the experiment MODULE as a
        real local subprocess that builds an engine and reports throughput."""
        from deepspeed_tpu.autotuning.scheduler import schedule_experiments
        cfg = {"train_batch_size": 4,
               "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
               "bf16": {"enabled": False},
               "_experiment": {"steps": 2,
                               "model": {"vocab_size": 64, "hidden_size": 32,
                                         "num_layers": 1, "num_heads": 2,
                                         "max_seq_len": 32,
                                         "attention_impl": "xla"}}}
        exps = schedule_experiments([cfg], hosts=["localhost"],
                                    results_dir=str(tmp_path / "exps"),
                                    poll_s=0.2, timeout_s=600)
        assert exps[0].status == "done", exps[0].error
        assert exps[0].metric > 0
