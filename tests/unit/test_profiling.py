"""Flops profiler + autotuner tests (reference:
flops_profiler/profiler.py:20, autotuning/autotuner.py:39)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.profiling import FlopsProfiler, get_model_profile
from tests.conftest import make_batch


def _tiny(**kw):
    base = dict(vocab_size=128, hidden_size=64, num_layers=4, num_heads=4,
                max_seq_len=64, dtype=jnp.float32, attention_impl="xla",
                tie_embeddings=True, position_type="learned",
                activation="gelu", norm_type="layernorm")
    base.update(kw)
    return TransformerConfig(**base)


class TestFlopsProfiler:
    def test_analytic_matches_6nd(self):
        """Forward flops of the LM must land near the 2*N*D estimate (dense
        matmul-dominated model: 2 flops/param/token forward)."""
        cfg = _tiny()
        model = make_model(cfg)
        params = model.init(jax.random.PRNGKey(0))
        B, S = 4, 64
        ids = jnp.zeros((B, S), jnp.int32)
        prof = get_model_profile(lambda p, i: model.apply(p, i), params, ids,
                                 backend_analysis=False)
        n_matmul_params = (
            cfg.num_layers * (4 * cfg.hidden_size ** 2
                              + 2 * cfg.hidden_size * cfg.ffn_dim)
            + cfg.hidden_size * cfg.vocab_size)  # lm head (tied)
        expect = 2 * n_matmul_params * B * S
        assert 0.8 * expect < prof["flops"] < 1.6 * expect, \
            (prof["flops"], expect)
        assert prof["params"] > 0
        assert "dot_general" in prof["flops_by_primitive"]
        # matmuls must dominate
        assert (prof["flops_by_primitive"]["dot_general"]
                > 0.6 * prof["flops"])

    def test_scan_layers_counted(self):
        """lax.scan over layers multiplies flops by depth: the 8-layer model
        must profile ~2x the 4-layer model."""
        def fwd(cfg):
            model = make_model(cfg)
            p = model.init(jax.random.PRNGKey(0))
            ids = jnp.zeros((2, 64), jnp.int32)
            return get_model_profile(lambda q, i: model.apply(q, i), p, ids,
                                     backend_analysis=False)["flops"]
        f4, f8 = fwd(_tiny(num_layers=4)), fwd(_tiny(num_layers=8))
        assert 1.6 < f8 / f4 < 2.2, (f4, f8)

    def test_engine_integration_prints_profile(self, devices8, caplog):
        model = make_model(_tiny())
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": False},
            "flops_profiler": {"enabled": True, "profile_step": 2},
            "steps_per_print": 1000})
        b = make_batch(8, 64, vocab=128)
        for _ in range(3):
            engine.train_batch(b)
        prof = getattr(engine, "flops_profile", None)
        assert prof is not None and prof["flops"] > 0
        assert prof["mfu"] > 0 and prof["step_latency_s"] > 0
        assert prof["flops_by_module"]


class TestAutotuner:
    def test_candidates_cover_mesh_space(self, devices8):
        from deepspeed_tpu.autotuning import Autotuner
        model = make_model(_tiny())
        t = Autotuner(model, {"train_batch_size": 16,
                              "autotuning": {"tuner_num_trials": 100}})
        cands = t.candidates()
        assert len(cands) > 4
        meshes = {tuple(sorted(c["mesh"]["axes"].items())) for c in cands}
        assert (("data", 8), ("tensor", 1)) in meshes
        assert (("fsdp", 8), ("tensor", 1)) in meshes
        assert any(dict(m).get("tensor") == 4 for m in meshes)

    def test_autotune_picks_valid_config(self, devices8, tmp_path):
        """End-to-end: autotuning enabled selects a runnable config at least
        as fast as the measured candidates, engine trains with it."""
        model = make_model(_tiny(num_layers=2))
        cfg = {
            "train_batch_size": 16,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": False},
            "autotuning": {"enabled": True, "tuner_num_trials": 3,
                           "tuner_early_stopping": 0,
                           "results_dir": str(tmp_path / "at")},
            "steps_per_print": 1000}
        engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        assert not engine.config.autotuning.enabled
        b = make_batch(16, 64, vocab=128)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(3)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        import json, os
        results = json.load(open(tmp_path / "at" / "results.json"))
        assert len(results) >= 1
        assert any(r["error"] is None for r in results)

    def test_failed_candidates_score_neg_inf(self, devices8):
        from deepspeed_tpu.autotuning import Autotuner
        model = make_model(_tiny(num_layers=2))
        t = Autotuner(model, {"train_batch_size": 16,
                              "optimizer": {"type": "adamw",
                                            "params": {"lr": 1e-3}},
                              "bf16": {"enabled": False}})
        trial = t.measure({"mesh": {"axes": {"data": 3}},  # 3 does not divide 8
                           "zero_optimization": {"stage": 0},
                           "gradient_accumulation_steps": 1})
        assert trial.error is not None
        assert trial.samples_per_sec == float("-inf")
