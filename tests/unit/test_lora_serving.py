"""Massive multi-tenancy: paged multi-LoRA serving + weight-only int8
decode matmuls (ISSUE 17).

The adapter slot pool is the paged-KV idea applied to READ-ONLY weights:
every registered adapter's A/B stacks live in host RAM (`AdapterStore`)
and page into a fixed device slot pool on demand (refcount + LRU in
`kv_cache.AdapterSlotPool`, slot 0 = the all-zero null adapter). One
decode quantum batches requests of DIFFERENT adapters in ONE dispatch —
a gathered einsum over per-row slot indices, one compile per pool shape.
The load-bearing contracts pinned here:

  - a mixed-adapter batch's greedy outputs EQUAL serving each adapter
    serially through an engine with that adapter merged into the dense
    weights (``apply_lora_dense``) — the parity bar, exact in f32;
  - slot pressure evicts LRU refcount-0 residents and re-pages on the
    next demand, token-identically; all-pinned exhaustion preempts the
    request back to the queue instead of failing the round;
  - ``load_peft_adapter`` round-trips PEFT's transposed per-layer
    lora_A/lora_B layout (+ alpha/rank scaling) into the slot tables;
  - ``weight_bits=8`` keeps the layer stacks int8-at-rest with f32
    per-channel scales (dequant fused in the matmul epilogue), >=0.9
    greedy agreement vs the unquantized engine, scales sharding with
    their columns under tp=2 with per-device bytes ~halved;
  - LoRA composes with the prefix cache (adapter requests neither
    publish nor match — content-only hashes would alias), chunked
    prefill and speculative decoding;
  - the lint corpus carries both defect twins: `adapter-slot-leak`
    (pool-growth) and `quantized-weight-replicated`
    (replication-over-budget), each next to its passing twin.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference.kv_cache import AdapterSlotPool, \
    BlockPoolExhausted
from deepspeed_tpu.inference.lora import (apply_lora_dense,
                                          make_random_adapter)
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.models.transformer import init_params
from deepspeed_tpu.parallel import MeshPlan, build_mesh

RANK = 4


def _cfg(**overrides):
    base = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                num_kv_heads=2, max_seq_len=256, position_type="rotary",
                activation="silu_glu", norm_type="rmsnorm",
                tie_embeddings=False, dtype=jnp.float32,
                attention_impl="xla")
    base.update(overrides)
    return TransformerConfig(**base)


def _serving(model, params, mesh=None, config=None, **serving):
    defaults = dict(max_seqs=2, block_size=16, max_model_len=128,
                    decode_quantum=4, prompt_bucket=16)
    defaults.update(serving)
    cfg = dict({"kv_cache_bits": 0}, **(config or {}))
    return deepspeed_tpu.init_serving(model, config=cfg, serving=defaults,
                                      dtype=jnp.float32, params=params,
                                      mesh=mesh)


@pytest.fixture(scope="module")
def base():
    """One config/model/raw-param tree shared module-wide. The params are
    RAW (unfused wq/wk/wv/wo) — ``apply_lora_dense`` needs them, and
    ``init_serving`` fuses internally either way, so every engine built
    from them is comparable."""
    cfg = _cfg()
    model = make_model(cfg)
    params = jax.device_get(init_params(jax.random.PRNGKey(0), cfg))
    # scale large enough that the delta MOVES greedy argmaxes on the tiny
    # model (the default 0.02 produces token-invisible deltas here, which
    # would let a gathers-slot-0-for-everyone bug pass parity vacuously)
    adapters = {a: make_random_adapter(cfg, RANK, seed=a, scale=0.2)
                for a in (1, 2, 3)}
    return cfg, model, params, adapters


def _reqs(seed=0, vocab=128, lens=(7, 21, 12, 30), news=(9, 6, 8, 5)):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, vocab, size=(n,)).astype(np.int32), k)
            for n, k in zip(lens, news)]


# ---------------------------------------------------------------------------
# The parity bar: mixed batch == serial per-adapter merged-dense serving
# ---------------------------------------------------------------------------

def test_mixed_adapter_batch_matches_merged_serial(base):
    """The headline contract: one engine serving interleaved tenants
    {base, 1, 2, 3} through the slot pool reproduces, token for token,
    each tenant served alone through an engine whose dense weights carry
    that adapter's A@B delta (the offline single-tenant merge)."""
    cfg, model, params, adapters = base
    prompts = _reqs(seed=1)
    aids = [0, 1, 2, 3]
    srv = _serving(model, params, max_seqs=4, adapter_slots=4,
                   lora_rank=RANK)
    for a, tabs in adapters.items():
        srv.register_adapter(a, tabs)
    mixed = srv.run([(p, n, a) for (p, n), a in zip(prompts, aids)])
    st = srv.stats()
    assert st["adapter_page_ins"] == 3.0
    for a in aids:
        merged = apply_lora_dense(params, cfg, adapters[a]) if a else params
        solo = _serving(model, merged)
        i = aids.index(a)
        out = solo.run([prompts[i]])
        np.testing.assert_array_equal(
            mixed[i], out[0],
            err_msg=f"adapter {a}: pooled != merged-dense serial")
    # the nonzero adapters actually CHANGED the tokens (a wiring bug that
    # gathers slot 0 for everyone would pass parity-of-nothing)
    plain = _serving(model, params, max_seqs=4).run(list(prompts))
    assert any(not np.array_equal(mixed[i], plain[i]) for i in (1, 2, 3))
    np.testing.assert_array_equal(mixed[0], plain[0])


def test_eviction_repage_token_identical(base):
    """2 usable slots, 3 tenants: the third page-in evicts the LRU
    refcount-0 resident; re-demanding the evicted adapter re-pages it
    and serves the SAME tokens. A resident re-acquire is a hit."""
    cfg, model, params, adapters = base
    srv = _serving(model, params, adapter_slots=3, lora_rank=RANK)
    for a, tabs in adapters.items():
        srv.register_adapter(a, tabs)
    prompt = _reqs(seed=2)[:1]
    ref = {}
    for a in (1, 2):
        ref[a] = srv.run([(prompt[0][0], prompt[0][1], a)])[a - 1]
    st = srv.stats()
    assert (st["adapter_page_ins"], st["adapter_evictions"]) == (2.0, 0.0)
    srv.run([(prompt[0][0], prompt[0][1], 3)])      # evicts LRU (adapter 1)
    st = srv.stats()
    assert (st["adapter_page_ins"], st["adapter_evictions"]) == (3.0, 1.0)
    again = srv.run([(prompt[0][0], prompt[0][1], 1)])   # re-page
    st = srv.stats()
    assert (st["adapter_page_ins"], st["adapter_evictions"]) == (4.0, 2.0)
    np.testing.assert_array_equal(ref[1], list(again.values())[0],
                                  err_msg="re-paged adapter diverged")
    srv.run([(prompt[0][0], prompt[0][1], 1)])           # resident: a hit
    assert srv.stats()["adapter_hits"] == 1.0


def test_all_pinned_exhaustion_preempts_not_fails(base):
    """Every slot pinned by in-flight tenants: the excess request queues
    (engine preempt) and completes once a slot frees — with the right
    tokens, not an error."""
    cfg, model, params, adapters = base
    srv = _serving(model, params, max_seqs=3, adapter_slots=3,
                   lora_rank=RANK)
    for a, tabs in adapters.items():
        srv.register_adapter(a, tabs)
    prompts = _reqs(seed=3, lens=(8, 8, 8), news=(12, 12, 4))
    outs = srv.run([(p, n, a) for (p, n), a in zip(prompts, (1, 2, 3))])
    assert len(outs) == 3
    solo = _serving(model, apply_lora_dense(params, cfg, adapters[3]))
    np.testing.assert_array_equal(outs[2], solo.run([prompts[2]])[0])


def test_adapter_validation(base):
    cfg, model, params, adapters = base
    plain = _serving(model, params)
    with pytest.raises(ValueError, match="adapter_slots=0"):
        plain.register_adapter(1, adapters[1])
    with pytest.raises(ValueError, match="adapter_slots=0"):
        plain.add_request(np.arange(4, dtype=np.int32), 4, adapter_id=1)
    srv = _serving(model, params, adapter_slots=3, lora_rank=RANK)
    with pytest.raises(ValueError, match="not registered"):
        srv.add_request(np.arange(4, dtype=np.int32), 4, adapter_id=9)
    with pytest.raises(ValueError, match="reserved"):
        srv.register_adapter(0, adapters[1])
    with pytest.raises(ValueError, match="num_slots=1"):
        AdapterSlotPool(1)


def test_slot_pool_host_accounting():
    """The pure-host pool: LRU order, refcount pinning, typed
    exhaustion."""
    p = AdapterSlotPool(3)
    s1, pi1 = p.acquire(7)
    assert pi1 and s1 != 0
    s2, pi2 = p.acquire(8)
    assert pi2 and s2 not in (0, s1)
    with pytest.raises(BlockPoolExhausted):
        p.acquire(9)                    # both pinned
    p.release(7)
    s3, pi3 = p.acquire(9)              # evicts 7 (LRU refcount-0)
    assert pi3 and s3 == s1 and p.evictions == 1
    s2b, pi2b = p.acquire(8)            # pinned resident: hit
    assert (s2b, pi2b) == (s2, False) and p.hits == 1


# ---------------------------------------------------------------------------
# PEFT round-trip
# ---------------------------------------------------------------------------

def test_peft_roundtrip_with_alpha(base):
    """A PEFT-layout state dict (transposed lora_A/lora_B per layer +
    adapter_config alpha) loads into the slot tables and serves exactly
    like the merged dense oracle with the SAME alpha/rank scaling."""
    from deepspeed_tpu.models.hf_import import load_peft_adapter
    cfg, model, params, adapters = base
    tabs = adapters[1]
    alpha = 2.0 * RANK                  # scale = alpha/rank = 2
    sd = {}
    for proj, (a, b) in tabs.items():
        for layer in range(cfg.num_layers):
            k = (f"base_model.model.model.layers.{layer}.self_attn."
                 f"{proj}_proj")
            sd[f"{k}.lora_A.weight"] = np.ascontiguousarray(a[layer].T)
            sd[f"{k}.lora_B.weight"] = np.ascontiguousarray(b[layer].T)
    loaded, got_alpha = load_peft_adapter(
        sd, cfg, adapter_config={"r": RANK, "lora_alpha": alpha})
    assert got_alpha == alpha
    for proj, (a, b) in tabs.items():
        np.testing.assert_allclose(loaded[proj][0], a, rtol=1e-6)
        np.testing.assert_allclose(loaded[proj][1], b, rtol=1e-6)
    srv = _serving(model, params, adapter_slots=2, lora_rank=RANK)
    srv.register_adapter(1, loaded, alpha=got_alpha)
    prompt = _reqs(seed=4)[:1]
    out = srv.run([(prompt[0][0], prompt[0][1], 1)])
    scaled = {p: (a, b * 2.0) for p, (a, b) in tabs.items()}
    solo = _serving(model, apply_lora_dense(params, cfg, scaled))
    np.testing.assert_array_equal(out[0], solo.run([prompt[0]])[0])


def test_peft_ragged_checkpoint_refuses(base):
    from deepspeed_tpu.models.hf_import import load_peft_adapter
    cfg, model, params, adapters = base
    a, b = adapters[1]["q"]
    sd = {"model.layers.0.self_attn.q_proj.lora_A.weight":
          np.ascontiguousarray(a[0].T),
          "model.layers.0.self_attn.q_proj.lora_B.weight":
          np.ascontiguousarray(b[0].T)}
    with pytest.raises(ValueError, match="missing lora_A/B"):
        load_peft_adapter(sd, cfg)      # layer 1 absent
    with pytest.raises(ValueError, match="no lora_A/lora_B"):
        load_peft_adapter({"unrelated.weight": a[0]}, cfg)


# ---------------------------------------------------------------------------
# Weight-only int8
# ---------------------------------------------------------------------------

def test_int8w_agreement_and_bytes(base):
    """weight_bits=8: int8-at-rest layer stacks with fused-dequant
    matmuls — >=0.9 greedy agreement vs the unquantized engine, layer
    bytes ~quartered vs f32 (int8 payload + f32 per-channel scales)."""
    cfg, model, params, adapters = base
    reqs = _reqs(seed=5)
    ref = _serving(model, params, max_seqs=4).run(list(reqs))
    srv = _serving(model, params, max_seqs=4, config={"weight_bits": 8})
    assert srv.stats()["weight_bits"] == 8.0
    outs = srv.run(list(reqs))
    agree = tot = 0
    for i in ref:
        n = min(len(ref[i]), len(outs[i]))
        agree += int(np.sum(np.asarray(ref[i][:n]) ==
                            np.asarray(outs[i][:n])))
        tot += max(len(ref[i]), len(outs[i]))
    assert agree / tot >= 0.9, f"greedy agreement {agree / tot:.3f}"
    layer_bytes = lambda tree: sum(         # noqa: E731
        int(np.prod(x.shape)) * x.dtype.itemsize
        for x in jax.tree.leaves(tree["layers"]))
    f32_bytes = layer_bytes(params)
    q_bytes = layer_bytes(jax.device_get(srv.engine.params))
    assert q_bytes < 0.3 * f32_bytes, (q_bytes, f32_bytes)


def test_int8w_tp2_parity_and_shard_halving(base):
    """tp=2 x weight_bits=8: the int8 payload AND its per-channel scales
    shard with their columns (per-device bytes halve for the sharded
    stacks) and greedy outputs are token-identical to the single-chip
    int8w engine."""
    from deepspeed_tpu.parallel.partitioning import sharded_bytes
    cfg, model, params, adapters = base
    reqs = _reqs(seed=6)
    srv1 = _serving(model, params, max_seqs=4, config={"weight_bits": 8})
    outs1 = srv1.run(list(reqs))
    mesh = build_mesh(MeshPlan(tensor=2), devices=jax.devices()[:2])
    srv2 = _serving(model, params, max_seqs=4, mesh=mesh,
                    config={"weight_bits": 8})
    assert (srv2.tp, srv2.ep) == (2, 1)
    wq = srv2.engine.params["layers"]["wq"]
    assert wq["q"].dtype == jnp.int8
    assert wq["q"].sharding.shard_shape(wq["q"].shape)[-1] * 2 \
        == wq["q"].shape[-1]
    assert wq["scale"].sharding.shard_shape(wq["scale"].shape)[-1] * 2 \
        == wq["scale"].shape[-1]
    per_dev = sharded_bytes(wq)
    logical = sum(int(np.prod(x.shape)) * x.dtype.itemsize
                  for x in jax.tree.leaves(wq))
    assert per_dev * 2 == logical
    outs2 = srv2.run(list(reqs))
    for rid in outs1:
        np.testing.assert_array_equal(outs1[rid], outs2[rid],
                                      err_msg=f"request {rid}")


def test_int8w_composes_with_lora(base):
    """The two tentpole halves together: int8 base weights + a pooled
    f32 adapter delta. The bar is agreement-shaped (int8 rounding), and
    the adapter must still visibly steer the tokens."""
    cfg, model, params, adapters = base
    srv = _serving(model, params, adapter_slots=2, lora_rank=RANK,
                   config={"weight_bits": 8})
    srv.register_adapter(1, adapters[1])
    prompt = _reqs(seed=7)[:2]
    outs = srv.run([(prompt[0][0], prompt[0][1], 1), prompt[1]])
    assert len(outs) == 2 and all(len(o) > 0 for o in outs.values())


# ---------------------------------------------------------------------------
# Composition: prefix cache / chunked prefill / speculation
# ---------------------------------------------------------------------------

def test_lora_composes_with_latency_features(base):
    """One engine with the full latency stack on (prefix cache, chunked
    prefill, n-gram speculation) serves the mixed-tenant load with the
    same tokens as the plain pooled engine, twice in a row (the second
    pass rides whatever the cache kept)."""
    cfg, model, params, adapters = base
    rng = np.random.default_rng(8)
    shared = rng.integers(0, cfg.vocab_size, size=(40,)).astype(np.int32)
    reqs = []
    for i, a in enumerate((0, 1, 2, 0)):
        tail = rng.integers(0, cfg.vocab_size, size=(5 + i,)
                            ).astype(np.int32)
        reqs.append((np.concatenate([shared, tail]), 8, a))
    plain = _serving(model, params, max_seqs=4, adapter_slots=3,
                     lora_rank=RANK)
    featured = _serving(model, params, max_seqs=4, adapter_slots=3,
                        lora_rank=RANK, enable_prefix_cache=True,
                        prefill_token_budget=32, spec_tokens=4)
    for a, tabs in adapters.items():
        plain.register_adapter(a, tabs)
        featured.register_adapter(a, tabs)
    ref = plain.run(list(reqs))
    refs = [ref[k] for k in sorted(ref)]
    for _ in range(2):
        outs = featured.run(list(reqs))
        vals = [outs[k] for k in sorted(outs)]
        for i, r in enumerate(refs):
            np.testing.assert_array_equal(r, vals[i],
                                          err_msg=f"request {i}")
    # adapter requests never publish or match: only the two base-model
    # requests (adapter_id 0) share cache entries
    st = featured.stats()
    assert st["prefix_hit_rows"] > 0


def test_adapter_requests_skip_prefix_cache(base):
    """IDENTICAL prompts under different adapters must not share KV: the
    adapter-1 request neither matches the base request's published
    prefix nor publishes one of its own (content-only hashes would alias
    across tenants)."""
    cfg, model, params, adapters = base
    srv = _serving(model, params, adapter_slots=2, lora_rank=RANK,
                   enable_prefix_cache=True)
    srv.register_adapter(1, adapters[1])
    prompt = np.arange(48, dtype=np.int32) % cfg.vocab_size
    srv.run([(prompt, 4)])              # publishes the base prefix
    srv.run([(prompt, 4, 1)])           # same content, different tenant
    srv.run([(prompt, 4, 1)])           # and again: still no match
    assert srv.stats()["prefix_hit_rows"] == 0.0
    srv.run([(prompt, 4)])              # base-model repeat DOES hit
    assert srv.stats()["prefix_hit_rows"] > 0


# ---------------------------------------------------------------------------
# Stats / drain / migrate
# ---------------------------------------------------------------------------

def test_stats_counters_and_reset(base):
    cfg, model, params, adapters = base
    srv = _serving(model, params, adapter_slots=3, lora_rank=RANK)
    srv.register_adapter(1, adapters[1])
    plain = _serving(model, params)
    assert srv.stats()["pool_bytes"] > plain.stats()["pool_bytes"]
    p = _reqs(seed=9)[0]
    srv.run([(p[0], p[1], 1)])
    st = srv.stats()
    assert st["adapter_page_ins"] == 1.0 and st["weight_bits"] == 0.0
    srv.reset_stats()
    st = srv.stats()
    assert (st["adapter_page_ins"], st["adapter_hits"],
            st["adapter_evictions"]) == (0.0, 0.0, 0.0)


def test_drain_migrate_carries_adapter_id(base, tmp_path):
    """A drained tenant request migrates onto a survivor that has the
    adapter registered and refuses (typed) one that doesn't — losing the
    adapter binding would silently serve base-model tokens."""
    from deepspeed_tpu.inference.serving import (ResumeIncompatible,
                                                 load_drain_state)
    cfg, model, params, adapters = base
    srv = _serving(model, params, adapter_slots=2, lora_rank=RANK)
    srv.register_adapter(1, adapters[1])
    srv.add_request(np.arange(10, dtype=np.int32), 6, adapter_id=1)
    srv.drain(str(tmp_path), source="r0")
    recs = load_drain_state(str(tmp_path))["requests"]
    assert recs[0]["adapter_id"] == 1
    bare = _serving(model, params, adapter_slots=2, lora_rank=RANK)
    with pytest.raises(ResumeIncompatible, match="adapter"):
        bare.accept_migration(recs, source="r0")
    nolora = _serving(model, params)
    with pytest.raises(ResumeIncompatible, match="adapter"):
        nolora.accept_migration(recs, source="r0")
    ok = _serving(model, params, adapter_slots=2, lora_rank=RANK)
    ok.register_adapter(1, adapters[1])
    assert ok.accept_migration(recs, source="r0") == [0]
    outs = {}
    while not ok.scheduler.done:
        for r in ok.step():
            outs[r.rid] = r.output
    solo = _serving(model, apply_lora_dense(params, cfg, adapters[1]))
    np.testing.assert_array_equal(
        outs[0], solo.run([(np.arange(10, dtype=np.int32), 6)])[0])


@pytest.mark.slow
def test_slow_multi_tenant_churn_soak(base):
    """Slow-tier certification: a 12-request rotating-tenant load with
    fewer usable slots than tenants (constant evict/re-page churn under
    all-pinned preemptions) and the full latency stack on (prefix cache
    + chunked prefill + speculation), pinned token-for-token against
    each tenant's merged-dense serial engine."""
    cfg, model, params, adapters = base
    rng = np.random.default_rng(11)
    reqs, aids = [], []
    for i in range(12):
        n = int(rng.integers(6, 40))
        reqs.append((rng.integers(0, cfg.vocab_size, size=(n,)
                                  ).astype(np.int32),
                     int(rng.integers(4, 10))))
        aids.append(i % 4)
    srv = _serving(model, params, max_seqs=3, adapter_slots=3,
                   lora_rank=RANK, enable_prefix_cache=True,
                   prefill_token_budget=32, spec_tokens=4)
    for a, tabs in adapters.items():
        srv.register_adapter(a, tabs)
    outs = srv.run([(p, n, a) for (p, n), a in zip(reqs, aids)])
    got = [outs[k] for k in sorted(outs)]
    st = srv.stats()
    assert st["adapter_evictions"] > 0      # the load actually churned
    for a in sorted(set(aids)):
        merged = apply_lora_dense(params, cfg, adapters[a]) if a else params
        solo = _serving(model, merged, max_seqs=3)
        idxs = [i for i in range(12) if aids[i] == a]
        souts = solo.run([reqs[i] for i in idxs])
        for i, o in zip(idxs, (souts[k] for k in sorted(souts))):
            np.testing.assert_array_equal(
                got[i], o, err_msg=f"request {i} (adapter {a})")


# ---------------------------------------------------------------------------
# Corpus: both directions
# ---------------------------------------------------------------------------

def test_adapter_slot_leak_corpus_both_directions():
    from deepspeed_tpu.analysis.corpus import CORPUS, run_corpus
    from deepspeed_tpu.analysis.serving_lint import audit_adapters
    assert "adapter-slot-leak" in CORPUS
    bad = run_corpus("adapter-slot-leak")
    assert not bad.ok
    assert any(f.rule == "pool-growth" for f in bad.findings)
    good = audit_adapters(correct=True)
    assert good.ok, [f.message for f in good.findings]


def test_quantized_weight_replicated_corpus_both_directions():
    from deepspeed_tpu.analysis.corpus import (CORPUS,
                                               int8_weight_pool_report,
                                               run_corpus)
    assert "quantized-weight-replicated" in CORPUS
    bad = run_corpus("quantized-weight-replicated")
    assert not bad.ok
    assert any(f.rule == "replication-over-budget" for f in bad.findings)
    good = int8_weight_pool_report(shard_weights=True)
    assert good.ok, [f.key for f in good.findings]
