"""Unified telemetry (PR 3 tentpole): in-graph accumulators, step tracing,
anomaly detection, sinks — without re-serializing the async pipeline.

Pins the acceptance contracts:
  * bit-for-bit training parity with telemetry on vs off over 20 fp16 steps
    including a forced overflow (the accumulators observe, never perturb);
  * ZERO added steady-state blocking fetches: between steps_per_print
    boundaries the hot loop performs no device_get at all, and each boundary
    performs exactly ONE batched device_get (telemetry leaf included);
  * CSV/JSONL sink round-trip, CSV handle caching, wandb per-step batching
    (via a stub module);
  * a captured step trace loads as valid Chrome-trace JSON;
  * the telemetry-leak graft-lint corpus entry is flagged by BOTH the
    donation and collective-audit analyzers.
"""

import json
import math
import os
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import telemetry as tel


# --------------------------------------------------------------------------
# shared toy model / config / batches (mirrors test_dataloader_prefetch)
# --------------------------------------------------------------------------

class ToyLinear:
    """Minimal ModelSpec whose loss can be pushed to an fp16 grad overflow
    on demand through the input magnitude."""

    name = "toy-linear"

    def __init__(self, d=8):
        self.d = d

    def init(self, rng):
        return {"w": jax.random.normal(rng, (self.d, self.d),
                                       jnp.float32) * 0.1}

    @property
    def logical_axes(self):
        return {"w": None}

    def loss_fn(self, params, batch, rng, deterministic):
        y = batch["x"] @ params["w"].astype(batch["x"].dtype)
        return jnp.mean(jnp.square(y).astype(jnp.float32))


def fp16_cfg(**overrides):
    cfg = {"train_batch_size": 16,
           "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
           "fp16": {"enabled": True, "initial_scale_power": 8},
           "bf16": {"enabled": False},
           "steps_per_print": 100}
    cfg.update(overrides)
    return cfg


def tel_cfg(**tel_overrides):
    t = {"enabled": True}
    t.update(tel_overrides)
    return t


def overflow_batches(n=20, boost_at=7):
    rng = np.random.default_rng(0)
    batches = [{"x": rng.normal(size=(16, 8)).astype(np.float32)}
               for _ in range(n)]
    batches[boost_at] = {"x": (batches[boost_at]["x"] * 1e8
                               ).astype(np.float32)}
    return batches


def params_bits(engine):
    w = np.asarray(jax.device_get(engine.state["params"]["w"]))
    return w.view(np.uint16)


# --------------------------------------------------------------------------
# (a) bit-for-bit parity: telemetry must observe, never perturb
# --------------------------------------------------------------------------

class TestTelemetryParity:
    def test_on_vs_off_bit_for_bit_with_overflow(self):
        batches = overflow_batches()
        off, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                           config=fp16_cfg())
        for b in batches:
            off.train_batch(b)

        on, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(), config=fp16_cfg(telemetry=tel_cfg()))
        on.train_batches(iter(batches), 20)

        assert off.global_steps == on.global_steps == 20
        assert off.skipped_steps == on.skipped_steps == 1
        assert off.get_loss_scale() == on.get_loss_scale()
        np.testing.assert_array_equal(params_bits(off), params_bits(on))

    def test_fused_k_steps_accumulate_and_match(self):
        """pipeline.fuse_steps=4 threads the accumulator leaf through the
        unrolled program: same bits, and the window stats count all 20 steps
        + the one overflow."""
        batches = overflow_batches()
        ref, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                           config=fp16_cfg())
        for b in batches:
            ref.train_batch(b)
        fused, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(),
            config=fp16_cfg(telemetry=tel_cfg(),
                            pipeline={"fuse_steps": 4, "in_flight": 2}))
        fused.train_batches(iter(batches), 20)
        np.testing.assert_array_equal(params_bits(ref), params_bits(fused))
        win = fused.drain_telemetry()
        assert win["steps"] == 20
        assert win["overflows"] == 1
        assert win["overflow_rate"] == pytest.approx(1 / 20)

    def test_window_stats_content(self):
        e, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(), config=fp16_cfg(telemetry=tel_cfg()))
        e.train_batches(iter(overflow_batches()), 20)
        win = e.drain_telemetry()
        assert win["steps"] == 20 and win["applied"] == 19
        assert math.isfinite(win["loss_mean"]) and win["loss_mean"] > 0
        assert win["gnorm_max"] >= win["gnorm_mean"] > 0
        # histogram counts every applied (non-overflow) step exactly once
        assert sum(win["gnorm_hist"]) == 19
        assert win["update_ratio_mean"] > 0
        # a second drain sees an EMPTY window (cumulative diff semantics)
        win2 = e.drain_telemetry()
        assert win2["steps"] == 0 and win2["overflows"] == 0

    def test_checkpoint_roundtrips_telemetry_leaf(self, tmp_path):
        e, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(), config=fp16_cfg(telemetry=tel_cfg()))
        e.train_batches(iter(overflow_batches(n=10)), 10)
        e.save_checkpoint(str(tmp_path), tag="ck")
        e2, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(), config=fp16_cfg(telemetry=tel_cfg()))
        e2.load_checkpoint(str(tmp_path), tag="ck")
        assert e2.skipped_steps == 1
        # cumulative counters restored; the window baseline restarts so the
        # first post-restore drain covers exactly the restored totals
        win = e2.drain_telemetry()
        assert win["steps"] == 10 and win["overflows"] == 1

    def test_loads_checkpoint_without_telemetry_leaf(self, tmp_path):
        """A telemetry-off checkpoint loads into a telemetry-on engine: the
        leaf is rebuilt fresh and keeps counting."""
        plain, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                             config=fp16_cfg())
        for b in overflow_batches(n=5, boost_at=2):
            plain.train_batch(b)
        plain.save_checkpoint(str(tmp_path), tag="legacy")
        e2, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(), config=fp16_cfg(telemetry=tel_cfg()))
        e2.load_checkpoint(str(tmp_path), tag="legacy")
        assert e2.global_steps == 5 and e2.skipped_steps == 1
        assert "telemetry" in e2.state
        e2.train_batches(iter(overflow_batches(n=5, boost_at=3)), 5)
        win = e2.drain_telemetry()
        assert win["steps"] == 5 and win["overflows"] == 1


# --------------------------------------------------------------------------
# (b) zero added steady-state blocking fetches
# --------------------------------------------------------------------------

class TestSingleBatchedFetch:
    def test_one_device_get_per_print_window(self, monkeypatch):
        # the LR schedule needs the device skip counter at boundaries — it
        # must ride the SAME batched fetch, not a second round trip
        e, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(),
            config=fp16_cfg(steps_per_print=10, telemetry=tel_cfg(),
                            scheduler={"type": "WarmupLR",
                                       "params": {"warmup_max_lr": 1e-2,
                                                  "warmup_num_steps": 5}}))
        batches = overflow_batches()

        calls = []
        real = jax.device_get

        def counting(x):
            calls.append(x)
            return real(x)

        monkeypatch.setattr(jax, "device_get", counting)
        e.train_batches(iter(batches), 20)
        # 20 steps / steps_per_print=10 -> exactly 2 boundary crossings,
        # each ONE batched device_get — telemetry adds ZERO fetches
        assert len(calls) == 2, f"expected 2 batched fetches, saw {len(calls)}"
        # and each fetch carried the telemetry leaf AND the skip counter
        # (for the LR schedule) alongside the metrics
        for c in calls:
            assert "_telemetry" in c and "loss" in c and "_skipped" in c

    def test_returned_metrics_stay_device_resident(self):
        e, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(),
            config=fp16_cfg(steps_per_print=100, telemetry=tel_cfg()))
        m = e.train_batch(overflow_batches(n=1, boost_at=0)[0])
        assert isinstance(m["loss"], jax.Array)  # not float()ed per step


# --------------------------------------------------------------------------
# accumulator / host-window math
# --------------------------------------------------------------------------

class TestAccumulators:
    def test_accumulate_and_window_diff(self):
        leaf = tel.init_leaf(8)
        step = jax.jit(lambda t, loss, g, ov, r: tel.accumulate(
            t, loss=loss, gnorm=g, overflow=ov, update_ratio=r))
        f = jnp.float32
        ov = jnp.asarray(False)
        leaf = step(leaf, f(1.0), f(2.0), ov, f(0.1))
        snap1 = jax.device_get(leaf)
        leaf = step(leaf, f(3.0), f(0.5), ov, f(0.3))
        leaf = step(leaf, f(999.0), f(1e9), jnp.asarray(True), f(0.0))
        snap2 = jax.device_get(leaf)
        win = tel.window_stats(snap2, snap1)
        assert win["steps"] == 2 and win["overflows"] == 1
        assert win["applied"] == 1
        assert win["loss_mean"] == pytest.approx(3.0)
        assert win["gnorm_mean"] == pytest.approx(0.5)
        assert win["update_ratio_mean"] == pytest.approx(0.3, rel=1e-5)
        # the overflow step contributed nothing to the value stats
        assert win["loss_max"] == pytest.approx(3.0)
        assert sum(win["gnorm_hist"]) == 1
        full = tel.window_stats(snap2, None)
        assert full["steps"] == 3 and full["applied"] == 2

    def test_hist_bucket_positions(self):
        leaf = tel.init_leaf(16)
        ov = jnp.asarray(False)
        for g in (2.0 ** -20, 1.0, 2.0 ** 20):  # below, mid, above range
            leaf = tel.accumulate(leaf, loss=jnp.float32(0), gnorm=jnp.float32(g),
                                  overflow=ov)
        hist = np.asarray(jax.device_get(leaf["gnorm_hist"]))
        assert hist[0] == 1 and hist[-1] == 1
        # gnorm=1 (log2=0): bucket 0 is the underflow bucket, bucket k>=1
        # covers [2^(HIST_LOG2_MIN+k-1), 2^(HIST_LOG2_MIN+k))
        assert hist[-tel.HIST_LOG2_MIN + 1] == 1
        assert hist.sum() == 3

    def test_all_overflow_window_has_no_loss_max(self):
        """A window with zero applied steps has no loss data: loss_max must
        come out None (not the -inf seed) so scalar sinks skip it."""
        leaf = tel.init_leaf(8)
        leaf = tel.accumulate(leaf, loss=jnp.float32(999.0),
                              gnorm=jnp.float32(1e9),
                              overflow=jnp.asarray(True))
        win = tel.window_stats(jax.device_get(leaf), None)
        assert win["steps"] == 1 and win["applied"] == 0
        assert win["loss_max"] is None

    def test_host_window_mirrors_device_semantics(self):
        hw = tel.HostWindow(8)
        hw.add({"loss": 1.0, "grad_norm": 2.0, "overflow": False})
        hw.add({"loss": np.float32(3.0), "grad_norm": np.float32(4.0),
                "overflow": np.asarray(True)})
        # drain consumes what the engine's batched device_get fetched
        snap = hw.drain(jax.device_get(hw.pending()))
        win = tel.window_stats(snap, None)
        assert win["steps"] == 2 and win["overflows"] == 1
        assert win["loss_mean"] == pytest.approx(1.0)
        assert win["gnorm_mean"] == pytest.approx(2.0)
        assert hw.pending() == []  # queue cleared


# --------------------------------------------------------------------------
# anomaly detection
# --------------------------------------------------------------------------

def _anomaly_cfg(**over):
    from deepspeed_tpu.config import AnomalyConfig
    return AnomalyConfig.from_dict(over)


def _win(**over):
    base = {"steps": 10, "applied": 10, "overflows": 0, "overflow_rate": 0.0,
            "loss_mean": 1.0, "loss_max": 1.0, "gnorm_mean": 1.0,
            "gnorm_max": 1.0, "update_ratio_mean": 0.01, "gnorm_hist": []}
    base.update(over)
    return base


class TestAnomalyDetector:
    def test_loss_spike_fires_after_warmup(self):
        det = tel.AnomalyDetector(_anomaly_cfg(warmup_windows=1,
                                               loss_spike_factor=2.0))
        assert det.observe(_win(), step=10) == []     # warmup: seeds only
        events = det.observe(_win(loss_mean=10.0), step=20)
        rules = {e["rule"] for e in events}
        assert "loss_spike" in rules
        spike = next(e for e in events if e["rule"] == "loss_spike")
        assert spike["severity"] == "critical"        # >2x factor x baseline
        assert spike["step"] == 20 and spike["baseline"] is not None

    def test_nonfinite_loss_is_always_critical(self):
        det = tel.AnomalyDetector(_anomaly_cfg())
        events = det.observe(_win(loss_mean=float("nan")), step=5)
        assert any(e["rule"] == "loss_spike" and e["severity"] == "critical"
                   for e in events)

    def test_overflow_burst_no_warmup(self):
        det = tel.AnomalyDetector(_anomaly_cfg(overflow_burst_rate=0.25))
        events = det.observe(
            _win(overflows=5, overflow_rate=0.5, applied=5), step=10)
        assert any(e["rule"] == "overflow_burst"
                   and e["severity"] == "critical" for e in events)

    def test_stall_regression(self):
        det = tel.AnomalyDetector(_anomaly_cfg(warmup_windows=1,
                                               stall_regression_factor=3.0))
        det.observe(_win(stall_ms_per_step=1.0), step=10)
        events = det.observe(_win(stall_ms_per_step=10.0), step=20)
        assert any(e["rule"] == "dispatch_stall" for e in events)

    def test_steady_state_stays_quiet(self):
        det = tel.AnomalyDetector(_anomaly_cfg())
        for i in range(5):
            assert det.observe(_win(), step=10 * (i + 1)) == []


# --------------------------------------------------------------------------
# step tracing / chrome trace export (acceptance: valid Chrome-trace JSON)
# --------------------------------------------------------------------------

class TestStepTracer:
    def test_span_window_and_chrome_export(self, tmp_path):
        tr = tel.StepTracer()
        with tr.span("dispatch"):
            pass
        with tr.span("block"):
            pass
        with tr.span("dispatch"):
            pass
        win = tr.drain_window()
        assert win["dispatch_count"] == 2 and win["block_count"] == 1
        assert win["dispatch_ms"] >= 0
        assert tr.drain_window() == {}  # window reset
        path = tr.export_chrome_trace(str(tmp_path / "trace.json"))
        with open(path) as f:
            data = json.load(f)
        assert isinstance(data["traceEvents"], list) and data["traceEvents"]
        for ev in data["traceEvents"]:
            assert ev["ph"] in ("X", "i")
            assert "name" in ev and "ts" in ev and "pid" in ev

    def test_engine_trace_covers_pipeline_phases(self, tmp_path):
        e, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(), config=fp16_cfg(telemetry=tel_cfg()))
        e.train_batches(iter(overflow_batches(n=8)), 8)
        path = e.export_trace(str(tmp_path / "step_trace.json"))
        with open(path) as f:
            data = json.load(f)
        names = {ev["name"] for ev in data["traceEvents"]}
        # dispatch + prefetch + data_wait + block phases all recorded
        assert {"dispatch", "prefetch", "data_wait", "block"} <= names

    def test_profiler_window_survives_fused_stride(self, tmp_path):
        """A fused K-step stride that jumps over [start, start+num) must
        start a shifted capture, not silently lose it; a run RESUMED past
        the window must not capture at all."""
        cfg = types.SimpleNamespace(enabled=True, start_step=10, num_steps=2,
                                    output_dir=str(tmp_path / "p"))
        tr = tel.StepTracer(trace_cfg=cfg)
        for step in (0, 4, 8, 12):   # stride 4 jumps over [10, 12)
            tr.maybe_profile(step)
        assert tr._profiling          # shifted capture opened at step 12
        tr.maybe_profile(16)
        assert not tr._profiling and tr._profile_done
        assert any(os.scandir(str(tmp_path / "p")))
        resumed = tel.StepTracer(trace_cfg=types.SimpleNamespace(
            enabled=True, start_step=10, num_steps=2,
            output_dir=str(tmp_path / "q")))
        resumed.maybe_profile(100000)  # checkpoint resume past the window
        assert resumed._profile_done and not resumed._profiling

    def test_export_requires_telemetry(self):
        e, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                         config=fp16_cfg())
        with pytest.raises(RuntimeError):
            e.export_trace("/tmp/never.json")


# --------------------------------------------------------------------------
# (c) sinks: CSV caching round-trip, JSONL round-trip, wandb batching
# --------------------------------------------------------------------------

def _sink_cfg(tmp_path, **over):
    d = {"enabled": True, "output_path": str(tmp_path), "job_name": "t",
         "team": None, "group": None, "project": None}
    d.update(over)
    return types.SimpleNamespace(**d)


class TestSinks:
    def test_csv_caches_handles_and_roundtrips(self, tmp_path):
        from deepspeed_tpu.monitor import CSVMonitor
        mon = CSVMonitor(_sink_cfg(tmp_path))
        mon.write_events([("Train/loss", 1.0, 1), ("Train/lr", 0.1, 1)])
        mon.write_events([("Train/loss", 2.0, 2), ("Train/lr", 0.2, 2)])
        # the satellite fix: handles are cached per metric, not reopened
        assert set(mon._files) == {"Train/loss", "Train/lr"}
        mon.flush()
        assert mon._files == {}  # flush closed them
        loss_csv = os.path.join(mon.dir, "Train_loss.csv")
        with open(loss_csv) as f:
            rows = list(f.read().strip().splitlines())
        assert rows[0].startswith("step,")        # header once
        assert len(rows) == 3
        assert rows[1].startswith("1,1.0") and rows[2].startswith("2,2.0")
        # writes after flush reopen and append without a second header
        mon.write_events([("Train/loss", 3.0, 3)])
        mon.flush()
        with open(loss_csv) as f:
            assert len(f.read().strip().splitlines()) == 4

    def test_jsonl_roundtrip_events_and_records(self, tmp_path):
        from deepspeed_tpu.monitor import JSONLMonitor
        path = str(tmp_path / "events.jsonl")
        mon = JSONLMonitor(path)
        mon.write_events([("telemetry/loss_mean", 1.5, 10)])
        mon.write_records([{"type": "anomaly", "rule": "loss_spike",
                            "severity": "critical", "step": 10,
                            "value": 9.0}])
        mon.flush()
        lines = [json.loads(l) for l in open(path)]
        assert lines[0] == {"type": "scalar", "name": "telemetry/loss_mean",
                            "value": 1.5, "step": 10,
                            "time": lines[0]["time"]}
        assert lines[1]["type"] == "anomaly"
        assert lines[1]["rule"] == "loss_spike"
        assert lines[1]["severity"] == "critical" and "time" in lines[1]

    def test_wandb_batches_one_log_per_step(self, tmp_path, monkeypatch):
        calls = []
        stub = types.ModuleType("wandb")
        stub.init = lambda **kw: None
        stub.log = lambda data, step=None: calls.append((dict(data), step))
        monkeypatch.setitem(sys.modules, "wandb", stub)
        from deepspeed_tpu.monitor import WandbMonitor
        mon = WandbMonitor(_sink_cfg(tmp_path))
        assert mon.enabled
        mon.write_events([("a", 1.0, 1), ("b", 2.0, 1),
                          ("a", 3.0, 2), ("b", 4.0, 2)])
        # the satellite fix: 4 events across 2 steps -> exactly 2 log calls
        assert calls == [({"a": 1.0, "b": 2.0}, 1), ({"a": 3.0, "b": 4.0}, 2)]

    def test_scalar_sinks_project_anomaly_records(self, tmp_path):
        from deepspeed_tpu.monitor import CSVMonitor
        mon = CSVMonitor(_sink_cfg(tmp_path))
        mon.write_records([{"type": "anomaly", "rule": "gnorm_drift",
                            "severity": "warning", "step": 7},
                           {"type": "telemetry_window", "step": 7}])
        mon.flush()
        files = os.listdir(mon.dir)
        assert "anomaly_gnorm_drift.csv" in files
        # the window record (no scalar projection) produced no file
        assert len(files) == 1


# --------------------------------------------------------------------------
# engine end-to-end: events fan out, anomalies fire, static join reports
# --------------------------------------------------------------------------

class TestEngineTelemetryEndToEnd:
    def test_jsonl_and_csv_fanout_with_anomaly(self, tmp_path):
        jsonl = str(tmp_path / "tel.jsonl")
        cfg = fp16_cfg(
            steps_per_print=10,
            csv_monitor={"enabled": True, "output_path": str(tmp_path),
                         "job_name": "job"},
            telemetry=tel_cfg(jsonl_path=jsonl,
                              anomaly={"enabled": True,
                                       "overflow_burst_rate": 0.05}))
        e, *_ = deepspeed_tpu.initialize(model=ToyLinear(), config=cfg)
        # window 1 contains the forced overflow -> overflow_burst fires
        e.train_batches(iter(overflow_batches(n=20, boost_at=3)), 20)
        e.monitor.flush()
        recs = [json.loads(l) for l in open(jsonl)]
        types_seen = {r["type"] for r in recs}
        assert {"scalar", "telemetry_window", "anomaly"} <= types_seen
        windows = [r for r in recs if r["type"] == "telemetry_window"]
        assert windows[0]["overflows"] == 1 and windows[0]["steps"] == 10
        assert windows[1]["steps"] == 10 and windows[1]["overflows"] == 0
        anomalies = [r for r in recs if r["type"] == "anomaly"]
        assert any(a["rule"] == "overflow_burst" for a in anomalies)
        csv_dir = os.path.join(str(tmp_path), "job")
        files = set(os.listdir(csv_dir))
        assert "telemetry_loss_mean.csv" in files
        assert "anomaly_overflow_burst.csv" in files
        # exactly ONE scalar row per fired anomaly (regression: the engine
        # events list + the write_records projection double-wrote these)
        bursts = [a for a in anomalies if a["rule"] == "overflow_burst"]
        with open(os.path.join(csv_dir, "anomaly_overflow_burst.csv")) as f:
            rows = f.read().strip().splitlines()
        assert len(rows) - 1 == len(bursts)  # header + one row per event

    def test_static_join_reports_mfu_and_comm_rate(self):
        e, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(),
            config=fp16_cfg(zero_optimization={"stage": 2},
                            telemetry=tel_cfg()))
        e.train_batches(iter(overflow_batches(n=10)), 10)
        win = e.drain_telemetry()
        assert win["steps_per_sec"] > 0
        # ZeRO-2 on an 8-way mesh moves real collective bytes every step
        assert win["modeled_comm_bytes_per_sec"] > 0
        assert 0 <= win.get("window_mfu", 0.0) < 1.0
        # the memory-lint join rides the same static cost: modeled peak
        # next to the allocator's measured high-water (when the transport
        # exposes memory_stats — CPU does)
        assert win["modeled_peak_hbm"] > 0

    def test_comms_logger_events_reach_monitor(self, tmp_path):
        jsonl = str(tmp_path / "comm.jsonl")
        from deepspeed_tpu.comm import comms_logger
        comms_logger.reset()
        cfg = fp16_cfg(steps_per_print=10,
                       comms_logger={"enabled": True},
                       telemetry=tel_cfg(jsonl_path=jsonl))
        e, *_ = deepspeed_tpu.initialize(model=ToyLinear(), config=cfg)
        try:
            from deepspeed_tpu import comm
            # trace-time + host-blocking records the engine should fan out
            comms_logger.record("all_reduce", "data", 4096)
            comms_logger.record_host("init_distributed", 1.5)
            e.train_batches(iter(overflow_batches(n=10)), 10)
            e.monitor.flush()
            recs = [json.loads(l) for l in open(jsonl)]
            names = {r["name"] for r in recs if r["type"] == "scalar"}
            assert any(n.startswith("comm/") and n.endswith("/count")
                       for n in names)
            assert any(n.startswith("comm/host_ms/") for n in names)
            # log_summary fans out through a monitor as well
            comm.log_summary(monitor=e.monitor, step=e.global_steps)
        finally:
            comms_logger.configure(enabled=False)

    def test_host_window_engine_plumbing(self):
        """Host-driven optimizer paths (NVMe swapper, layer-streamed
        executor) have no jitted optimizer apply, so the engine mirrors the
        accumulator host-side. Those executors need pinned_host memory this
        CPU backend lacks (pre-existing test_offload/test_infinity skips),
        so the host mirror is wired in directly: per-step metric scalars
        queue UN-fetched and drain at the boundary's one batched fetch."""
        from deepspeed_tpu.telemetry import HostWindow
        e, *_ = deepspeed_tpu.initialize(
            model=ToyLinear(),
            config=fp16_cfg(steps_per_print=5,
                            telemetry=tel_cfg(static_join=False)))
        e._tel_in_graph = False          # what a host-driven init would set
        e._tel_host = HostWindow(16)
        for b in overflow_batches(n=5, boost_at=1):
            e.train_batch(b)
        win = e.telemetry_window()       # drained at the step-5 boundary
        assert win is not None
        assert win["steps"] == 5 and win["overflows"] == 1
        assert math.isfinite(win["loss_mean"]) and win["loss_mean"] > 0
        assert sum(win["gnorm_hist"]) == 4
        assert e._tel_host.pending() == []


# --------------------------------------------------------------------------
# graft-lint: the telemetry-leak corpus entry (CI tooling satellite)
# --------------------------------------------------------------------------

class TestTelemetryLeakCorpus:
    def test_both_analyzers_flag_the_leak(self, devices8):
        from deepspeed_tpu.analysis.corpus import run_corpus
        report = run_corpus("telemetry-leak", devices=devices8[:2])
        assert not report.ok
        rules = {f.rule for f in report.findings}
        assert "donation-missing" in rules          # un-donated stats leaf
        assert "collective-census-drift" in rules   # per-step collective
        leak = next(f for f in report.findings
                    if f.rule == "donation-missing")
        assert "telemetry" in leak.ident


# --------------------------------------------------------------------------
# config surface
# --------------------------------------------------------------------------

class TestTelemetryConfig:
    def test_defaults_off_and_validation(self):
        from deepspeed_tpu.config import Config, ConfigError
        cfg = Config.load({})
        assert not cfg.telemetry.enabled
        assert cfg.telemetry.anomaly.enabled
        with pytest.raises(ConfigError):
            Config.load({"telemetry": {"gnorm_hist_buckets": 1}})
        with pytest.raises(ConfigError):
            Config.load({"telemetry": {"trace": {"num_steps": 0}}})

    def test_sections_parse(self):
        from deepspeed_tpu.config import Config
        cfg = Config.load({"telemetry": {
            "enabled": True, "jsonl_path": "/tmp/x.jsonl",
            "trace": {"enabled": True, "start_step": 5, "num_steps": 3},
            "anomaly": {"loss_spike_factor": 4.0}}})
        assert cfg.telemetry.trace.start_step == 5
        assert cfg.telemetry.anomaly.loss_spike_factor == 4.0
        assert cfg.telemetry.jsonl_path == "/tmp/x.jsonl"
