"""graft-proto tests: each wire-schema rule both directions on fixture
sources, the checked-in registry against the live tree (clean, no
baseline), baseline round-trip, golden wire fixtures replayed against
the CURRENT readers, the seeded corpus twins, and CLI exit codes."""

import json
import os
import textwrap
import types

import pytest

from deepspeed_tpu.analysis import proto_lint
from deepspeed_tpu.analysis.proto_lint import (audit_drain_schema_skew,
                                               load_registry, scan_package,
                                               scan_source)

_FIXTURES = os.path.join(os.path.dirname(__file__), "..", "fixtures",
                         "proto")


def _rules(report):
    return {f.rule for f in report.findings}


def _snippet(src):
    return textwrap.dedent(src)


# --------------------------------------------------------------------------
# each rule, defect and corrected twin on synthetic boundary modules
# --------------------------------------------------------------------------

class TestUnversionedPayload:
    def test_versionless_drain_writer_flagged(self):
        rep = scan_source(_snippet("""
            import json

            def save(path, requests):
                state = {"source": "r0", "requests": requests}
                with open(path, "w") as f:
                    json.dump(state, f)
        """), "corpus/fix_writer.py")
        assert "unversioned-payload" in _rules(rep)
        f = next(f for f in rep.findings if f.rule == "unversioned-payload")
        assert "corpus/fix_writer.py:" in f.message

    def test_versioned_drain_writer_clean(self):
        rep = scan_source(_snippet("""
            import json

            def save(path, requests):
                state = {"version": 3, "source": "r0",
                         "requests": requests}
                with open(path, "w") as f:
                    json.dump(state, f)
        """), "corpus/fix_writer.py")
        assert "unversioned-payload" not in _rules(rep)

    def test_unmatched_boundary_sink_without_version_flagged(self):
        # a NEW payload shape json-dumped at a boundary without any
        # version/schema key: the lint can't match it, but it can still
        # demand versioning discipline
        rep = scan_source(_snippet("""
            import json

            def save(path, rows):
                blob = {"rows": rows, "kind": "sidecar"}
                with open(path, "w") as f:
                    json.dump(blob, f)
        """), "deepspeed_tpu/inference/fix_sidecar.py")
        assert "unversioned-payload" in _rules(rep)

    def test_event_emit_without_schema_flagged_and_with_schema_clean(self):
        bad = scan_source(_snippet("""
            from deepspeed_tpu.robustness import events as rb_events

            def announce(rid):
                rb_events.emit("request_handoff", rid=rid, src="a",
                               dst="b")
        """), "deepspeed_tpu/inference/fix_events.py")
        assert "unversioned-payload" in _rules(bad)
        good = scan_source(_snippet("""
            from deepspeed_tpu.robustness import events as rb_events

            def announce(rid):
                rb_events.emit("request_handoff", schema=1, rid=rid,
                               src="a", dst="b")
        """), "deepspeed_tpu/inference/fix_events.py")
        assert "unversioned-payload" not in _rules(good)


class TestSchemaBreakingChange:
    def test_unregistered_version_flagged(self):
        rep = scan_source(_snippet("""
            import json

            def save(path, requests):
                state = {"version": 9, "source": "r0",
                         "requests": requests}
                with open(path, "w") as f:
                    json.dump(state, f)
        """), "corpus/fix_writer.py")
        assert "schema-breaking-change" in _rules(rep)

    def test_unregistered_field_flagged_registered_clean(self):
        bad = scan_source(_snippet("""
            import json

            def save(path, requests):
                state = {"version": 3, "source": "r0",
                         "sampler_state": 7, "requests": requests}
                with open(path, "w") as f:
                    json.dump(state, f)
        """), "corpus/fix_writer.py")
        assert "schema-breaking-change" in _rules(bad)
        f = next(f for f in bad.findings
                 if f.rule == "schema-breaking-change")
        assert "sampler_state" in f.message
        good = scan_source(_snippet("""
            import json

            def save(path, requests):
                state = {"version": 3, "source": "r0", "rng_counter": 7,
                         "requests": requests}
                with open(path, "w") as f:
                    json.dump(state, f)
        """), "corpus/fix_writer.py")
        assert "schema-breaking-change" not in _rules(good)

    def test_missing_required_field_flagged(self):
        # a kv-payload built without its crc/geometry: the handoff
        # reader's validation contract is broken at the writer
        rep = scan_source(_snippet("""
            def export(rows, blocks, data):
                return {"schema": 1, "rows": rows, "blocks": blocks,
                        "data": data}
        """), "deepspeed_tpu/inference/fix_kv.py")
        assert "schema-breaking-change" in _rules(rep)
        f = next(f for f in rep.findings
                 if f.rule == "schema-breaking-change")
        assert "crc" in f.message or "geometry" in f.message

    def test_event_with_unregistered_field_flagged(self):
        rep = scan_source(_snippet("""
            from deepspeed_tpu.robustness import events as rb_events

            def announce(rid):
                rb_events.emit("request_handoff", schema=1, rid=rid,
                               src="a", dst="b", flavor="spicy")
        """), "deepspeed_tpu/inference/fix_events.py")
        assert "schema-breaking-change" in _rules(rep)

    def test_version_constant_resolved_through_schemas_module(self):
        # writers reference DRAIN_STATE_VERSION, not a literal: the lint
        # resolves it via inference/schemas.py so a legal bump there is
        # seen without editing every writer
        rep = scan_source(_snippet("""
            import json
            from deepspeed_tpu.inference.schemas import DRAIN_STATE_VERSION

            def save(path, requests):
                state = {"version": DRAIN_STATE_VERSION, "source": "r0",
                         "requests": requests}
                with open(path, "w") as f:
                    json.dump(state, f)
        """), "corpus/fix_writer.py")
        assert "schema-breaking-change" not in _rules(rep)
        assert "unversioned-payload" not in _rules(rep)


def _reader_registry(relpath, qual="read_drain", keep_checksum=False):
    """Registry overlay: the fixture function is the ONLY registered
    drain-state reader (so skew/checksum findings anchor there)."""
    reg = load_registry()
    reg["schemas"]["drain-state"]["readers"] = [f"{relpath}::{qual}"]
    if not keep_checksum:
        reg["schemas"]["drain-state"].pop("checksum", None)
    reg["schemas"]["drain-request"]["readers"] = []
    reg["schemas"]["kv-payload"]["readers"] = []
    return reg


class TestReaderWriterSkew:
    _RELPATH = "corpus/fix_reader.py"

    def test_bare_read_of_version_gated_field_flagged(self):
        rep = scan_source(_snippet("""
            import json

            def read_drain(path):
                with open(path) as f:
                    state = json.load(f)
                return state["engine"], state["requests"]
        """), self._RELPATH, registry=_reader_registry(self._RELPATH))
        assert "reader-writer-skew" in _rules(rep)
        f = next(f for f in rep.findings if f.rule == "reader-writer-skew")
        assert "engine" in f.message and f"{self._RELPATH}:" in f.message

    def test_get_defaulted_read_clean(self):
        rep = scan_source(_snippet("""
            import json

            def read_drain(path):
                with open(path) as f:
                    state = json.load(f)
                return state.get("engine"), state["requests"]
        """), self._RELPATH, registry=_reader_registry(self._RELPATH))
        assert "reader-writer-skew" not in _rules(rep)

    def test_membership_guarded_read_clean(self):
        # the serving.py idiom: `if "engine" in state:` before indexing
        rep = scan_source(_snippet("""
            import json

            def read_drain(path):
                with open(path) as f:
                    state = json.load(f)
                if "engine" in state:
                    return state["engine"], state["requests"]
                return None, state["requests"]
        """), self._RELPATH, registry=_reader_registry(self._RELPATH))
        assert "reader-writer-skew" not in _rules(rep)

    def test_always_required_field_bare_read_clean(self):
        # `requests` is required by EVERY registered version: indexing it
        # bare can never skew
        rep = scan_source(_snippet("""
            import json

            def read_drain(path):
                with open(path) as f:
                    state = json.load(f)
                return state["requests"]
        """), self._RELPATH, registry=_reader_registry(self._RELPATH))
        assert "reader-writer-skew" not in _rules(rep)


class TestChecksumGap:
    _RELPATH = "corpus/fix_reader.py"

    def test_unverified_reader_of_checksummed_schema_flagged(self):
        rep = scan_source(_snippet("""
            import json

            def read_drain(path):
                with open(path) as f:
                    state = json.load(f)
                return state.get("engine"), state["requests"]
        """), self._RELPATH,
            registry=_reader_registry(self._RELPATH, keep_checksum=True))
        assert "checksum-gap" in _rules(rep)
        f = next(f for f in rep.findings if f.rule == "checksum-gap")
        assert "validate_tag" in f.message

    def test_reader_through_integrity_chain_clean(self):
        rep = scan_source(_snippet("""
            import json
            import os
            from deepspeed_tpu.robustness import integrity

            def read_drain(save_dir):
                tag = integrity.newest_valid_tag(save_dir)
                with open(os.path.join(save_dir, tag, "state.json")) as f:
                    state = json.load(f)
                return state.get("engine"), state["requests"]
        """), self._RELPATH,
            registry=_reader_registry(self._RELPATH, keep_checksum=True))
        assert "checksum-gap" not in _rules(rep)


# --------------------------------------------------------------------------
# the live tree against the checked-in registry
# --------------------------------------------------------------------------

class TestPackageScan:
    def test_package_clean_even_without_baseline(self):
        # the acceptance gate: after this PR's schema centralization the
        # tree has zero findings to allowlist (no baseline file exists)
        rep = scan_package()
        assert rep.ok, [f"{f.rule}: {f.message}" for f in rep.findings]
        assert not os.path.exists(proto_lint.DEFAULT_BASELINE)

    def test_census_covers_the_fleet_surface(self):
        rep = scan_package()
        census = rep.meta["proto"]
        # drain writers (engine + router residue + lint stub), heartbeat,
        # manifest, kv export at least
        assert census["payload_sites"] >= 10
        assert census["matched_payloads"] >= 8
        assert census["emit_sites"] >= 20
        # every registered reader function must actually be found —
        # a renamed reader silently dropping out of scope is how skew
        # checks rot
        registry = load_registry()
        registered = sum(len(s.get("readers", ()))
                         for s in registry["schemas"].values())
        assert census["reader_fns"] == registered

    def test_baseline_round_trip_suppresses(self):
        src = _snippet("""
            import json

            def save(path, requests):
                state = {"source": "r0", "requests": requests}
                with open(path, "w") as f:
                    json.dump(state, f)
        """)
        rep = scan_source(src, "corpus/fix_writer.py")
        assert not rep.ok
        rep2 = scan_source(src, "corpus/fix_writer.py")
        rep2.apply_baseline(rep.baseline_dict())
        assert rep2.ok and rep2.suppressed


# --------------------------------------------------------------------------
# golden wire fixtures: payloads from every era the fleet ever wrote,
# replayed against the CURRENT readers
# --------------------------------------------------------------------------

def _fixture(name):
    with open(os.path.join(_FIXTURES, name + ".json")) as f:
        return json.load(f)


class TestGoldenFixtures:
    @pytest.mark.parametrize("name", ["drain_state_v1", "drain_state_v2",
                                      "drain_state_v2_nogeometry",
                                      "drain_state_v3"])
    def test_drain_fixture_loads_through_current_reader(self, name,
                                                        tmp_path):
        from deepspeed_tpu.inference.schemas import DRAIN_STATE_VERSIONS
        from deepspeed_tpu.inference.serving import load_drain_state
        from deepspeed_tpu.robustness import integrity
        payload = _fixture(name)
        tag_dir = tmp_path / "drain_fixture"
        tag_dir.mkdir()
        integrity.atomic_write(str(tag_dir / "state.json"),
                               json.dumps(payload), what="golden fixture")
        integrity.write_manifest(str(tag_dir))
        integrity.write_commit_marker(str(tag_dir))
        state = load_drain_state(str(tmp_path), tag="drain_fixture")
        assert state["tag"] == "drain_fixture"
        assert int(state.get("version", 1)) in DRAIN_STATE_VERSIONS
        # exactly the fields the failover/resume paths index bare —
        # every era's records must satisfy them
        assert state["requests"]
        for rec in state["requests"]:
            assert int(rec["rid"]) >= 0
            assert isinstance(rec["prompt"], list) and rec["prompt"]
            assert int(rec["max_new_tokens"]) >= 1
            assert isinstance(rec.get("generated", []), list)
        # the version-gated fields stay .get-guarded in the reader
        state.get("engine"), state.get("rng_counter"), state.get("source")

    def test_registry_pins_every_drain_fixture_era(self):
        registry = load_registry()
        versions = registry["schemas"]["drain-state"]["versions"]
        for name in ("drain_state_v1", "drain_state_v2",
                     "drain_state_v2_nogeometry", "drain_state_v3"):
            payload = _fixture(name)
            ver = str(payload.get("version", 1))
            assert ver in versions, (name, ver)
            spec = versions[ver]
            known = set(spec["required"]) | set(spec["optional"])
            assert set(payload) <= known, (name, set(payload) - known)
            missing = set(spec["required"]) - set(payload)
            assert not missing, (name, missing)

    def test_roleless_heartbeat_readable_and_lands_in_both_tier(self,
                                                               tmp_path):
        from deepspeed_tpu.elasticity.rendezvous import FileRendezvous
        from deepspeed_tpu.inference.fleet import (FleetConfig,
                                                   FleetController)
        payload = _fixture("heartbeat_roleless")
        (tmp_path / f"hb_{payload['host']}.json").write_text(
            json.dumps(payload))
        rdzv = FileRendezvous(str(tmp_path), "observer", dead_after_s=60.0,
                              clock=lambda: payload["ts"] + 1.0)
        beats = rdzv.read_heartbeats()
        assert payload["host"] in beats
        assert "schema" not in beats[payload["host"]]   # the pre-schema era
        assert payload["host"] in rdzv.live_host_info()
        # the CURRENT fleet controller resolves a role-less meta to the
        # "both" tier (the pre-disaggregation deployment shape)
        router = types.SimpleNamespace(
            config=types.SimpleNamespace(
                store_dir=str(tmp_path),
                clock=lambda: payload["ts"] + 1.0),
            replicas={})
        ctl = FleetController(router, spawn=lambda n, r: None,
                              config=FleetConfig(role="both",
                                                 dead_after_s=60.0))
        assert payload["host"] in ctl._tier()


# --------------------------------------------------------------------------
# corpus twins + CLI
# --------------------------------------------------------------------------

class TestCorpusTwins:
    def test_defect_fires_both_rules_with_provenance(self):
        rep = audit_drain_schema_skew(correct=False)
        assert not rep.ok
        rules = _rules(rep)
        assert "schema-breaking-change" in rules
        assert "reader-writer-skew" in rules
        for f in rep.findings:
            assert f.data["file"] and f.data["line"] > 0

    def test_corrected_twin_holds(self):
        rep = audit_drain_schema_skew(correct=True)
        assert rep.ok, [f.message for f in rep.findings]


class TestCLI:
    def test_tree_scan_exit_zero(self, capsys):
        assert proto_lint.main([]) == 0
        out = capsys.readouterr().out
        assert "proto_lint: OK" in out

    def test_corpus_gate_exit_zero(self, capsys):
        assert proto_lint.main(["--corpus"]) == 0
        out = capsys.readouterr().out
        assert "defect twin FIRES" in out
        assert "corrected twin holds" in out
        assert " at corpus/drain_schema_skew.py:" in out

    def test_json_report_parses(self, capsys):
        assert proto_lint.main(["--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["ok"] is True

    def test_write_baseline_round_trip(self, tmp_path, capsys):
        # a deliberately dirty single-module "tree": baseline it, rescan
        root = tmp_path / "deepspeed_tpu"
        root.mkdir()
        (root / "dirty.py").write_text(_snippet("""
            import json

            def save(path, requests):
                state = {"source": "r0", "requests": requests}
                with open(path, "w") as f:
                    json.dump(state, f)
        """))
        base = tmp_path / "baseline.json"
        assert proto_lint.main(["--root", str(root), "--no-baseline"]) == 1
        capsys.readouterr()
        assert proto_lint.main(["--root", str(root), "--baseline",
                                str(base), "--write-baseline"]) == 0
        assert base.exists()
        assert proto_lint.main(["--root", str(root), "--baseline",
                                str(base)]) == 0
