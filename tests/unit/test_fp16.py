"""Dynamic loss scaler state machine vs the reference semantics.

Reference: ``deepspeed/runtime/fp16/loss_scaler.py`` DynamicLossScaler
.update_scale — shrink-on-exhausted-hysteresis, growth every scale_window
clean steps, hysteresis replenished at the growth boundary (default) or every
clean step (consecutive_hysteresis=True).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.fp16 import (LossScaleState, has_overflow,
                                        init_loss_scale, update_loss_scale)


def step(state, overflow, **kw):
    return update_loss_scale(state, jnp.asarray(overflow), **kw)


def scale(state):
    return float(np.asarray(state.scale))


def hys(state):
    return int(np.asarray(state.hysteresis))


class TestDynamicLossScale:
    def test_overflow_consumes_hysteresis_before_shrink(self):
        s = init_loss_scale(initial_scale_power=16, hysteresis=2)
        s = step(s, True, max_hysteresis=2)
        assert scale(s) == 2.0 ** 16 and hys(s) == 1  # tolerated
        s = step(s, True, max_hysteresis=2)
        assert scale(s) == 2.0 ** 15  # exhausted -> shrink

    def test_shrink_does_not_replenish_hysteresis(self):
        # reference keeps cur_hysteresis at 1 after a shrink: the next
        # overflow shrinks again immediately
        s = init_loss_scale(initial_scale_power=16, hysteresis=2)
        s = step(s, True, max_hysteresis=2)
        s = step(s, True, max_hysteresis=2)   # shrink, hys stays 1
        assert hys(s) == 1
        s = step(s, True, max_hysteresis=2)
        assert scale(s) == 2.0 ** 14

    def test_default_replenishes_only_at_growth_boundary(self):
        s = init_loss_scale(initial_scale_power=16, hysteresis=2)
        s = step(s, True, max_hysteresis=2, scale_window=4)
        assert hys(s) == 1
        # clean steps below the window do NOT replenish
        for _ in range(3):
            s = step(s, False, max_hysteresis=2, scale_window=4)
            assert hys(s) == 1
        # 4th clean step: growth boundary -> scale grows AND hysteresis refills
        s = step(s, False, max_hysteresis=2, scale_window=4)
        assert scale(s) == 2.0 ** 17 and hys(s) == 2

    def test_consecutive_hysteresis_replenishes_every_clean_step(self):
        s = init_loss_scale(initial_scale_power=16, hysteresis=2)
        s = step(s, True, max_hysteresis=2, consecutive_hysteresis=True)
        assert hys(s) == 1
        s = step(s, False, max_hysteresis=2, consecutive_hysteresis=True)
        assert hys(s) == 2

    def test_overflow_resets_growth_window(self):
        s = init_loss_scale(initial_scale_power=16, hysteresis=1)
        for _ in range(3):
            s = step(s, False, scale_window=4, max_hysteresis=1)
        s = step(s, True, scale_window=4, max_hysteresis=1)  # shrink + reset
        for _ in range(3):
            s = step(s, False, scale_window=4, max_hysteresis=1)
        assert scale(s) == 2.0 ** 15  # not yet regrown

    def test_min_scale_floor(self):
        s = LossScaleState(scale=jnp.asarray(2.0, jnp.float32),
                           good_steps=jnp.zeros((), jnp.int32),
                           hysteresis=jnp.ones((), jnp.int32))
        s = step(s, True, min_scale=1.0, max_hysteresis=1)
        s = step(s, True, min_scale=1.0, max_hysteresis=1)
        assert scale(s) == 1.0

    def test_has_overflow(self):
        good = {"a": jnp.ones((4,)), "b": jnp.zeros((2, 2))}
        bad = {"a": jnp.ones((4,)), "b": jnp.array([[1.0, jnp.inf], [0, 0]])}
        assert not bool(has_overflow(good))
        assert bool(has_overflow(bad))
