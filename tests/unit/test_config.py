"""Config system tests (reference: tests/unit/runtime/test_ds_config_dict.py /
test_ds_config_model.py)."""

import pytest

from deepspeed_tpu.config import Config, ConfigError


def test_defaults():
    cfg = Config.load({})
    assert cfg.zero_optimization.stage == 0
    assert cfg.bf16.enabled
    assert not cfg.fp16.enabled


def test_batch_triad_full():
    cfg = Config.load({"train_batch_size": 32,
                       "train_micro_batch_size_per_gpu": 4,
                       "gradient_accumulation_steps": 2})
    cfg.resolve_batch_size(dp_world_size=4)
    assert cfg.train_batch_size == 32


def test_batch_triad_mismatch():
    cfg = Config.load({"train_batch_size": 32,
                       "train_micro_batch_size_per_gpu": 4,
                       "gradient_accumulation_steps": 4})
    with pytest.raises(ConfigError):
        cfg.resolve_batch_size(dp_world_size=4)


def test_batch_triad_solve_gas():
    cfg = Config.load({"train_batch_size": 64, "train_micro_batch_size_per_gpu": 4})
    cfg.resolve_batch_size(dp_world_size=4)
    assert cfg.gradient_accumulation_steps == 4


def test_batch_triad_solve_from_micro_only():
    cfg = Config.load({"train_micro_batch_size_per_gpu": 2})
    cfg.resolve_batch_size(dp_world_size=8)
    assert cfg.train_batch_size == 16
    assert cfg.gradient_accumulation_steps == 1


def test_zero_stage_validation():
    with pytest.raises(ConfigError):
        Config.load({"zero_optimization": {"stage": 5}})


def test_offload_param_requires_stage3():
    with pytest.raises(ConfigError):
        Config.load({"zero_optimization": {
            "stage": 2, "offload_param": {"device": "cpu"}}})


def test_fp16_bf16_conflict_resolves():
    cfg = Config.load({"fp16": {"enabled": True}, "bf16": {"enabled": True}})
    assert cfg.fp16.enabled and not cfg.bf16.enabled


def test_optimizer_type_alias():
    cfg = Config.load({"optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    assert cfg.optimizer.name == "AdamW"
    assert cfg.optimizer.params["lr"] == 1e-3


def test_unknown_optimizer_rejected():
    with pytest.raises(ConfigError):
        Config.load({"optimizer": {"type": "nope"}})


def test_compute_dtype():
    import jax.numpy as jnp
    assert Config.load({}).compute_dtype == jnp.bfloat16
    assert Config.load({"fp16": {"enabled": True}, "bf16": {"enabled": False}}).compute_dtype == jnp.float16
    assert Config.load({"bf16": {"enabled": False}}).compute_dtype == jnp.float32


def test_roundtrip_to_dict():
    cfg = Config.load({"zero_optimization": {"stage": 2}})
    d = cfg.to_dict()
    assert d["zero_optimization"]["stage"] == 2
    cfg2 = Config.load(d)
    assert cfg2.zero_optimization.stage == 2
