"""Quantizer + compression tests (reference: csrc/quantization/*,
compression/compress.py, compression/basic_layer.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.compression import (
    CompressionTransform, init_compression, redundancy_clean)
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.ops.quantizer import (
    dequantize, fake_quant, quantize, quantize_tree, dequantize_tree)
from tests.conftest import make_batch


class TestQuantizer:
    @pytest.mark.parametrize("bits,symmetric", [(8, True), (8, False),
                                                (4, True), (4, False)])
    def test_roundtrip_error_bounded(self, bits, symmetric):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(64, 128)),
                        jnp.float32)
        qt = quantize(x, bits=bits, symmetric=symmetric, num_groups=64)
        y = dequantize(qt)
        # max error <= one quantization step per group
        err = np.abs(np.asarray(y - x, np.float32))
        steps = np.asarray(qt.scale).reshape(-1, 1)
        g_err = err.reshape(64, -1)
        assert (g_err <= steps * 0.75 + 1e-6).all(), g_err.max()

    def test_int4_packs_half_bytes(self):
        x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 256)))
        q8 = quantize(x, bits=8, num_groups=4)
        q4 = quantize(x, bits=4, num_groups=4)
        assert q4.q.size == q8.q.size // 2
        assert q4.q.dtype == jnp.uint8

    def test_fake_quant_straight_through(self):
        x = jnp.asarray(np.random.default_rng(2).normal(size=(32, 32)),
                        jnp.float32)
        g = jax.grad(lambda w: jnp.sum(fake_quant(w, bits=8) * 3.0))(x)
        np.testing.assert_allclose(np.asarray(g), 3.0)  # STE: grad passes

    def test_tree_quantization(self):
        tree = {"big": jnp.ones((128, 128)), "small": jnp.ones((4,))}
        qt = quantize_tree(tree, bits=8, min_size=1000)
        from deepspeed_tpu.ops.quantizer import QuantizedTensor
        assert isinstance(qt["big"], QuantizedTensor)
        assert not isinstance(qt["small"], QuantizedTensor)
        back = dequantize_tree(qt)
        np.testing.assert_allclose(np.asarray(back["big"]), 1.0, rtol=1e-2)


def _comp_cfg(**sections):
    base = {}
    for name, params in sections.items():
        base[name] = {"shared_parameters": {"enabled": True,
                                            "schedule_offset": 2},
                      "different_groups": {"g1": {"params": params,
                                                  "modules": ["*"]}}}
    return base


class TestCompression:
    def test_sparse_mask_ratio(self):
        t = CompressionTransform(_comp_cfg(
            sparse_pruning={"dense_ratio": 0.25}))
        w = jnp.asarray(np.random.default_rng(0).normal(size=(64, 64)),
                        jnp.float32)
        out = t.apply({"layers": {"w_in": w}}, step=10)["layers"]["w_in"]
        nz = np.count_nonzero(np.asarray(out))
        assert abs(nz / w.size - 0.25) < 0.02
        # before the schedule offset: untouched
        pre = t.apply({"layers": {"w_in": w}}, step=0)["layers"]["w_in"]
        np.testing.assert_array_equal(np.asarray(pre), np.asarray(w))

    def test_row_pruning(self):
        t = CompressionTransform(_comp_cfg(row_pruning={"dense_ratio": 0.5}))
        w = jnp.asarray(np.random.default_rng(1).normal(size=(32, 16)),
                        jnp.float32)
        out = np.asarray(t.apply({"w": w}, step=5)["w"])
        zero_rows = (out == 0).all(axis=1).sum()
        assert zero_rows == 16

    def test_head_pruning(self):
        t = CompressionTransform(_comp_cfg(
            head_pruning={"dense_ratio": 0.5, "num_heads": 4}))
        w = jnp.asarray(np.random.default_rng(2).normal(size=(64, 32)),
                        jnp.float32)
        out = np.asarray(t.apply({"wo": w}, step=5)["wo"])
        per_head = out.reshape(4, 16, 32)
        dead = [(per_head[h] == 0).all() for h in range(4)]
        assert sum(dead) == 2

    @pytest.mark.slow
    def test_engine_qat_training(self, devices8):
        """QAT: weight fake-quant active after schedule_offset; training
        still converges and masters stay full precision."""
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False},
            "compression_training": {
                "weight_quantization": {
                    "shared_parameters": {"enabled": True,
                                          "schedule_offset": 2},
                    "different_groups": {
                        "q8": {"params": {"target_bits": 8},
                               "modules": ["*"]}}}},
            "steps_per_print": 1000})
        b = make_batch(8, 32, vocab=64)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(8)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        # master weights are NOT quantized (distinct values beyond 256 levels)
        w = np.asarray(jax.device_get(
            engine.state["params"]["layers"]["w_in"])).reshape(-1)
        assert len(np.unique(np.round(w, 6))) > 300

    def test_redundancy_clean_exports_pruned(self):
        cfg = _comp_cfg(sparse_pruning={"dense_ratio": 0.5})
        params = {"w": jnp.asarray(
            np.random.default_rng(3).normal(size=(64, 64)), jnp.float32)}
        out = redundancy_clean(params, cfg)
        assert np.count_nonzero(np.asarray(out["w"])) <= 0.51 * 64 * 64

    def test_channel_pruning(self):
        t = CompressionTransform(_comp_cfg(
            channel_pruning={"dense_ratio": 0.5}))
        w = jnp.asarray(np.random.default_rng(4).normal(size=(16, 32)),
                        jnp.float32)
        out = np.asarray(t.apply({"w": w}, step=5)["w"])
        zero_cols = (out == 0).all(axis=0).sum()
        assert zero_cols == 16

    @pytest.mark.slow
    def test_activation_quant_engine(self, devices8):
        """Activation quantization: post-norm activations are fake-quantized
        (STE) once schedule_offset is reached; training converges."""
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False},
            "compression_training": {
                "activation_quantization": {
                    "shared_parameters": {"enabled": True,
                                          "schedule_offset": 3},
                    "different_groups": {
                        "a8": {"params": {"bits": 8}, "modules": ["*"]}}}},
            "steps_per_print": 1000})
        b = make_batch(8, 32, vocab=64)
        assert not engine._act_quant_on
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(8)]
        assert engine._act_quant_on
        assert engine.model.config.activation_quant_bits == 8
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    @pytest.mark.slow
    def test_layer_reduction_engine(self, devices8):
        """layer_reduction: the engine trains a keep_number-layer student."""
        model = make_model(TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=4, num_heads=2,
            max_seq_len=64, dtype=jnp.float32, attention_impl="xla"))
        engine, *_ = deepspeed_tpu.initialize(model=model, config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "bf16": {"enabled": False},
            "compression_training": {
                "layer_reduction": {"enabled": True, "keep_number": 2,
                                    "teacher_layer": [0, 3]}},
            "steps_per_print": 1000})
        assert engine.model.config.num_layers == 2
        w = engine.state["params"]["layers"]["w_in"]
        assert w.shape[0] == 2
        b = make_batch(8, 32, vocab=64)
        losses = [float(engine.train_batch(b)["loss"]) for _ in range(6)]
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    @pytest.mark.slow
    def test_student_from_teacher_and_distill(self):
        """Layer-reduced student initialized from teacher layers + KD loss
        (reference: compress.py student_initialization + kd pairing)."""
        from deepspeed_tpu.compression import (make_distillation_loss,
                                               student_params_from_teacher)
        from deepspeed_tpu.models.transformer import init_params
        import dataclasses as dc
        tcfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=4,
                                 num_heads=2, max_seq_len=64,
                                 dtype=jnp.float32, attention_impl="xla")
        scfg = dc.replace(tcfg, num_layers=2)
        teacher = init_params(jax.random.PRNGKey(0), tcfg)
        student = student_params_from_teacher(teacher, [0, 3])
        assert student["layers"]["w_in"].shape[0] == 2
        np.testing.assert_array_equal(
            np.asarray(student["layers"]["w_in"][1]),
            np.asarray(teacher["layers"]["w_in"][3]))

        loss_fn = make_distillation_loss(scfg, teacher, tcfg, alpha=0.5,
                                         temperature=2.0)
        b = make_batch(4, 16, vocab=64)
        batch = {"input_ids": jnp.asarray(b["input_ids"])}
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch))(student)
        assert np.isfinite(float(loss))
        gnorm = sum(float(jnp.sum(g.astype(jnp.float32) ** 2))
                    for g in jax.tree.leaves(grads))
        assert gnorm > 0
