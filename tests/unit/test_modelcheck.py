"""graft-check tests: the six control-plane invariants hold for the
shipped router + fleet controller over exhaustive bounded event spaces,
the seeded defect twins (fenceless failover, the PR-19 cooldown
off-by-one) fire with replayable event traces, and the explorer's
replay/trace-id machinery round-trips."""

import pytest

from deepspeed_tpu.robustness import modelcheck
from deepspeed_tpu.robustness.modelcheck import (FENCE_ALPHABET,
                                                 FULL_ALPHABET, Harness,
                                                 audit_events, explore,
                                                 parse_trace, run_sequence,
                                                 trace_id)


def _rules(report):
    return {f.rule for f in report.findings}


# --------------------------------------------------------------------------
# the explorer itself
# --------------------------------------------------------------------------

class TestTraceIds:
    def test_round_trip(self):
        assert trace_id((0, 1, 0, 0)) == "e0.1.0.0"
        assert parse_trace("e0.1.0.0") == [0, 1, 0, 0]
        assert parse_trace(trace_id((7,))) == [7]

    def test_bad_id_rejected(self):
        with pytest.raises(ValueError):
            parse_trace("x3.1")


class TestExplorerDeterminism:
    def test_same_trace_same_violations(self, tmp_path):
        factory = lambda base: Harness(base, fenced=False)  # noqa: E731
        idxs = [FENCE_ALPHABET.index(e)
                for e in ("probe", "stale", "probe", "probe")]
        a = run_sequence(factory, FENCE_ALPHABET, idxs, str(tmp_path / "a"))
        b = run_sequence(factory, FENCE_ALPHABET, idxs, str(tmp_path / "b"))
        assert a == b
        assert any(v.startswith("double-serve") for v in a)

    def test_exhaustive_count(self):
        # lengths 1..2 over a 4-event alphabet = 4 + 16 worlds
        res = explore(lambda base: Harness(base, fenced=True),
                      FENCE_ALPHABET, depth=2)
        assert res["explored"] == 20 and not res["failures"]


# --------------------------------------------------------------------------
# the shipped control plane holds every invariant
# --------------------------------------------------------------------------

class TestInvariantsHold:
    def test_full_alphabet_exhaustive_depth_2(self):
        # all 8 events (breaker, fencing, torn tags, fleet ticks) over
        # every 1- and 2-event world: 72 sequences, six invariants each
        res = explore(
            lambda base: Harness(base, controller=True, cooldown_ticks=2,
                                 hot=True),
            FULL_ALPHABET, depth=2)
        assert res["explored"] == 72
        assert not res["failures"], res["failures"][:2]

    def test_fencing_alphabet_exhaustive_depth_4(self):
        # the fencing-focused space at the corpus depth: heartbeats go
        # stale, partitions stick, and the fenced sweep never migrates a
        # live replica's work
        res = explore(lambda base: Harness(base, fenced=True),
                      FENCE_ALPHABET, depth=4)
        assert res["explored"] == 340
        assert not res["failures"], res["failures"][:2]

    def test_kill_with_drain_migrates_everything(self, tmp_path):
        # a supervised kill drains through the integrity chain: the
        # failover must migrate every queued request (lost == 0) and the
        # survivor must complete them exactly once
        h = Harness(str(tmp_path), fenced=True)
        for ev in ("probe", "probe", "kill", "stale", "probe", "probe",
                   "probe"):
            h.apply(ev)
        assert not h.violations, h.violations
        fo = h._rb.history("replica_failover")
        assert fo and fo[-1]["lost"] == 0 and fo[-1]["drain_tag"]
        assert all(v == ["r1"] or v == ["r0"]
                   for v in h.completions.values())
        h.close()

    def test_torn_tag_never_counts_as_evidence(self, tmp_path):
        # a torn (uncommitted) drain tag + heartbeat silence must not
        # migrate the still-alive replica's work
        h = Harness(str(tmp_path), fenced=True)
        for ev in ("probe", "torn", "stale", "probe", "probe"):
            h.apply(ev)
        assert not h.violations, h.violations
        assert not h._rb.history("replica_failover")
        h.close()


# --------------------------------------------------------------------------
# seeded twins: defect fires with a replayable trace, corrected holds
# --------------------------------------------------------------------------

class TestFencelessFailover:
    def test_defect_found_as_double_serve_with_replayable_trace(self):
        rep = audit_events("fenceless-failover", correct=False)
        assert not rep.ok
        assert "double-serve" in _rules(rep)
        assert "unfenced-migration" in _rules(rep)
        f = next(f for f in rep.findings if f.rule == "double-serve")
        assert f.data["replay_id"].startswith("e")
        # the printed trace id replays to the same violation
        again = modelcheck.replay("fenceless-failover",
                                  f.data["replay_id"], correct=False)
        assert any(v.startswith("double-serve") for v in again)

    def test_corrected_router_holds_over_full_space(self):
        rep = audit_events("fenceless-failover", correct=True)
        assert rep.ok, [f.message for f in rep.findings]
        assert rep.meta["audit"]["explored"] == 340

    def test_shallow_defect_run_reports_explorer_miss(self):
        # the regression floor for the explorer itself: a depth too
        # shallow to reach the bug must say so, not pass silently
        rep = audit_events("fenceless-failover", correct=False, depth=1)
        assert "explorer-miss" in _rules(rep)


class TestCooldownOffByOne:
    def test_prefix_tick_fires_cooldown_discipline(self):
        # the PR-19 defect: decrement-before-gate makes cooldown_ticks=1
        # suppress ZERO ticks — two consecutive scale-ups, no quiet tick
        rep = audit_events("cooldown-off-by-one", correct=False)
        assert not rep.ok
        assert "cooldown-discipline" in _rules(rep)
        f = next(f for f in rep.findings
                 if f.rule == "cooldown-discipline")
        assert f.data["replay_id"] == "e0.0"      # [tick, tick]
        assert "only 0 observe tick" in f.message

    def test_fixed_tick_holds_and_acts_after_exactly_the_cooldown(self):
        rep = audit_events("cooldown-off-by-one", correct=True)
        assert rep.ok, [f.message for f in rep.findings]

    def test_stuck_cooldown_also_flagged(self, tmp_path):
        # the other direction of exactness: a controller that never
        # leaves cooldown under clean sustained pressure is stuck
        from deepspeed_tpu.inference.fleet import FleetController

        class _Stuck(FleetController):
            def tick(self):
                out = super().tick()
                if out is not None:
                    self._cooldown = 10 ** 9   # jam after the first action
                return out

        h = Harness(str(tmp_path), controller=True, cooldown_ticks=1,
                    hot=True)
        h.ctl = _Stuck(h.router, h.ctl.spawn, h.fleet_cfg)
        for _ in range(5):
            h.apply("tick")
        assert any(v.startswith("cooldown-discipline") and "stuck" in v
                   for v in h.violations), h.violations
        h.close()


class TestCLI:
    def test_corpus_gate_exit_zero(self, capsys):
        assert modelcheck.main(["--corpus"]) == 0
        out = capsys.readouterr().out
        assert out.count("defect twin FIRES") == 2
        assert out.count("corrected twin holds") == 3
        assert "--replay e" in out
        assert "modelcheck: OK" in out

    def test_single_audit_exit_codes(self, capsys):
        assert modelcheck.main(["--audit", "fenceless-failover",
                                "--defect"]) == 1
        capsys.readouterr()
        assert modelcheck.main(["--audit", "cooldown-off-by-one"]) == 0

    def test_list_corpus(self, capsys):
        assert modelcheck.main(["--list-corpus"]) == 0
        out = capsys.readouterr().out
        assert "fenceless-failover" in out
        assert "control-plane-full" in out


# --------------------------------------------------------------------------
# slow tier: the shipped depth + one deeper ring (run_slow.sh, PROTO_BUDGET)
# --------------------------------------------------------------------------

@pytest.mark.slow
class TestExhaustiveSoak:
    def test_control_plane_full_space_at_shipped_depth(self):
        rep = audit_events("control-plane-full", correct=True)
        assert rep.ok, [f.message for f in rep.findings]
        assert rep.meta["audit"]["explored"] == 584     # 8 + 64 + 512

    def test_fencing_space_one_ring_deeper(self):
        res = explore(lambda base: Harness(base, fenced=True),
                      FENCE_ALPHABET, depth=5)
        assert res["explored"] == 1364
        assert not res["failures"], res["failures"][:2]
