"""Comms microbenchmark harness (reference: benchmarks/communication/
run_all.py + utils.py get_bw conventions)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from deepspeed_tpu.benchmarks.communication import (OPS, _bus_factor,
                                                    run_comm_bench)


@pytest.fixture
def mesh8():
    return Mesh(np.array(jax.devices()[:8]), ("data",))


def test_all_ops_run(mesh8):
    rows = run_comm_bench(mesh8, sizes=[1 << 12], iters=3)
    by_op = {r["op"]: r for r in rows}
    assert set(by_op) == set(OPS)
    for op, r in by_op.items():
        assert "error" not in r, (op, r)
        assert r["world"] == 8
        assert r["latency_us"] > 0
        assert r["alg_bw_gbps"] > 0
        # both fields are independently rounded to 4 decimals — on a loaded
        # box the measured bandwidths can be ~1e-3 Gbps, where the rounding
        # quantum (2x 1e-4) exceeds any relative tolerance: allow it
        # absolutely so the convention check doesn't flake under load
        assert r["bus_bw_gbps"] == pytest.approx(
            r["alg_bw_gbps"] * _bus_factor(op, 8), rel=5e-2, abs=3e-4)


def test_bus_factor_convention():
    # reference get_bw: allreduce 2(n-1)/n, allgather/reducescatter (n-1)/n
    assert _bus_factor("psum", 4) == pytest.approx(1.5)
    assert _bus_factor("all_gather", 4) == pytest.approx(0.75)
    assert _bus_factor("ppermute", 4) == 1.0
    assert _bus_factor("psum", 1) == 1.0


def test_size_sweep_rows(mesh8):
    rows = run_comm_bench(mesh8, sizes=[1 << 12, 1 << 14], ops=("psum",),
                          iters=2)
    assert len(rows) == 2
    assert rows[0]["elements"] < rows[1]["elements"]


def test_single_device_mesh_runs():
    """On the real chip the mesh may be a single device — the harness must
    still produce rows (latency of the degenerate collective)."""
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("data",))
    rows = run_comm_bench(mesh1, sizes=[1 << 10], ops=("psum", "all_gather"),
                          iters=2)
    assert all("error" not in r for r in rows), rows


@pytest.mark.slow
def test_embedding_grad_stance_bench():
    """Sparse-embedding-grad N/A-by-design evidence (reference:
    engine.py:2302-2369 sparse allreduce): the microbench runs, the dense
    reduce-scatter shard beats the static-shape sparse wire at realistic
    shapes, and the engine reports the stance."""
    from deepspeed_tpu.benchmarks import bench_embedding_grad
    out = bench_embedding_grad(vocab=512, hidden=32, batch=2, seq=16,
                               layers=1, steps=2)
    assert out["step_full_s"] > 0 and out["step_frozen_embed_s"] > 0
    assert np.isfinite(out["embed_grad_cost_pct"])
    # the byte math at a REALISTIC shape: gpt2-vocab, 4k tokens, dp=8 —
    # dense moves ~6.4MB/chip, the sparse wire ~28MB/chip
    dense = 50257 * 256 * 4 / 8
    sparse = 8 * 512 * (256 * 4 + 4) * 7
    assert dense < sparse


def test_engine_sparse_gradients_stance():
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models import TransformerConfig, make_model
    model = make_model(TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        max_seq_len=32, dtype=jnp.float32, attention_impl="xla"))
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8, "sparse_gradients": True,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": False}, "steps_per_print": 1000})
    assert engine.sparse_gradients_enabled() is False
