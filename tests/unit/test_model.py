"""Model zoo tests: shapes, loss sanity, determinism, GQA/rotary variants."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import (
    TransformerConfig, make_model, gpt2_config, llama_config, logical_axes)
from tests.conftest import make_batch


def test_forward_shapes(tiny_model, rng):
    params = tiny_model.init(rng)
    batch = make_batch(2, 16)
    logits = tiny_model.apply(params, jnp.asarray(batch["input_ids"]))
    assert logits.shape == (2, 16, 256)
    assert logits.dtype == jnp.float32


def test_loss_finite_and_near_uniform_init(tiny_model, rng):
    params = tiny_model.init(rng)
    batch = make_batch(4, 32)
    loss = tiny_model.loss_fn(params, batch, None, True)
    assert np.isfinite(float(loss))
    # at init, loss should be near ln(vocab)
    assert abs(float(loss) - np.log(256)) < 1.0


def test_causality(tiny_model, rng):
    """Changing a future token must not affect earlier logits."""
    params = tiny_model.init(rng)
    ids = jnp.asarray(make_batch(1, 16)["input_ids"])
    logits1 = tiny_model.apply(params, ids)
    ids2 = ids.at[0, 10].set((ids[0, 10] + 7) % 256)
    logits2 = tiny_model.apply(params, ids2)
    np.testing.assert_allclose(np.asarray(logits1[0, :10]),
                               np.asarray(logits2[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(logits1[0, 10:]), np.asarray(logits2[0, 10:]))


def test_gqa_rotary_rmsnorm(rng):
    cfg = TransformerConfig(vocab_size=128, hidden_size=64, num_layers=2,
                            num_heads=4, num_kv_heads=2, position_type="rotary",
                            activation="silu_glu", norm_type="rmsnorm",
                            tie_embeddings=False, dtype=jnp.float32,
                            attention_impl="xla", max_seq_len=64)
    model = make_model(cfg)
    params = model.init(rng)
    assert "lm_head" in params
    assert "w_gate" in params["layers"]
    assert "bq" not in params["layers"]
    logits = model.apply(params, jnp.asarray(make_batch(2, 16, vocab=128)["input_ids"]))
    assert logits.shape == (2, 16, 128)
    assert np.isfinite(np.asarray(logits)).all()


def test_logical_axes_structure_matches_params(rng):
    for cfg in [gpt2_config("125m", num_layers=2, hidden_size=64, num_heads=4,
                            vocab_size=128, dtype=jnp.float32),
                llama_config("tiny", dtype=jnp.float32)]:
        model = make_model(cfg)
        params = jax.eval_shape(model.init, rng)
        axes = model.logical_axes
        assert jax.tree.structure(jax.tree.map(lambda x: 0, params)) == \
            jax.tree.structure(jax.tree.map(lambda x: 0, axes,
                                            is_leaf=lambda x: x is None or isinstance(x, tuple)))
        # every axes tuple rank must match the param rank (the path walk
        # goes through the jax<=0.4.37 compat helper: jax.tree only grew
        # leaves_with_path later — the PR-16 hf_import fallback)
        from deepspeed_tpu.models.hf_import import _leaves_with_path
        flat_p = _leaves_with_path(params)
        axes_map = {jax.tree_util.keystr(k): v for k, v in
                    _leaves_with_path(axes, is_leaf=lambda x: x is None or isinstance(x, tuple))}
        for path, leaf in flat_p:
            a = axes_map[jax.tree_util.keystr(path)]
            assert a is None or len(a) == len(leaf.shape), f"{path}: {a} vs {leaf.shape}"


def test_scan_vs_unrolled(rng):
    kw = dict(vocab_size=128, hidden_size=64, num_layers=3, num_heads=4,
              dtype=jnp.float32, attention_impl="xla", max_seq_len=64)
    m_scan = make_model(TransformerConfig(scan_layers=True, **kw))
    m_unroll = make_model(TransformerConfig(scan_layers=False, **kw))
    params = m_scan.init(rng)
    ids = jnp.asarray(make_batch(2, 16, vocab=128)["input_ids"])
    np.testing.assert_allclose(np.asarray(m_scan.apply(params, ids)),
                               np.asarray(m_unroll.apply(params, ids)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_remat_matches(rng):
    kw = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
              dtype=jnp.float32, attention_impl="xla", max_seq_len=64)
    m = make_model(TransformerConfig(**kw))
    m_remat = make_model(TransformerConfig(remat=True, remat_policy="dots_saveable", **kw))
    params = m.init(rng)
    batch = make_batch(2, 16, vocab=128)
    g1 = jax.grad(lambda p: m.loss_fn(p, batch, None, True))(params)
    g2 = jax.grad(lambda p: m_remat.loss_fn(p, batch, None, True))(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_grad_flows_to_all_params(tiny_model, rng):
    params = tiny_model.init(rng)
    batch = make_batch(2, 16)
    grads = jax.grad(lambda p: tiny_model.loss_fn(p, batch, None, True))(params)
    for path, g in jax.tree.leaves_with_path(grads):
        assert np.isfinite(np.asarray(g)).all(), path
        # pos_embed rows beyond seq_len legitimately have zero grad
        if "pos_embed" not in str(path):
            assert np.abs(np.asarray(g)).sum() > 0, f"zero grad at {path}"


@pytest.mark.slow
def test_attn_windows_band_mask_and_grads(rng):
    """Per-layer local-attention windows (GPT-Neo/Mistral pattern): the
    band bites once seq > window while in-window positions stay exact;
    grads flow, differ from the global-attention grads, and the scan and
    unrolled window threading agree. (Numerical parity against HF's real
    local attention lives in test_hf_import's GPT-Neo tests.) Slow tier:
    numerical-parity suite (fwd+bwd on four model variants, ~8s; re-tiered
    with the PR-6 quick additions to hold the 180s tier budget)."""
    kw = dict(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
              dtype=jnp.float32, attention_impl="xla", max_seq_len=64,
              position_type="learned")
    m_win = make_model(TransformerConfig(attn_windows=(0, 4), **kw))
    m_glob = make_model(TransformerConfig(**kw))
    params = m_win.init(rng)
    batch = make_batch(2, 16, vocab=128)
    ids = jnp.asarray(batch["input_ids"])
    # windowed forward differs from global once seq > window
    out_w = np.asarray(m_win.apply(params, ids))
    out_g = np.asarray(m_glob.apply(params, ids))
    assert np.abs(out_w - out_g).max() > 1e-4
    # positions within the window see identical context (causal prefix):
    # the first `window` positions of every sequence must match exactly
    np.testing.assert_allclose(out_w[:, :4], out_g[:, :4], rtol=1e-5,
                               atol=1e-6)
    g_w = jax.grad(lambda p: m_win.loss_fn(p, batch, None, True))(params)
    assert all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree.leaves(g_w))
    g_g = jax.grad(lambda p: m_glob.loss_fn(p, batch, None, True))(params)
    diffs = [float(np.abs(np.asarray(a) - np.asarray(b)).max())
             for a, b in zip(jax.tree.leaves(g_w), jax.tree.leaves(g_g))]
    assert max(diffs) > 1e-5   # the band mask reaches the backward
    # scan and unrolled paths agree under windows
    m_unroll = make_model(TransformerConfig(attn_windows=(0, 4),
                                            scan_layers=False, **kw))
    np.testing.assert_allclose(np.asarray(m_unroll.apply(params, ids)),
                               out_w, rtol=1e-5, atol=1e-5)


def test_attn_windows_length_mismatch_raises(rng):
    cfg = TransformerConfig(vocab_size=64, hidden_size=32, num_layers=3,
                            num_heads=2, dtype=jnp.float32,
                            attention_impl="xla", max_seq_len=32,
                            attn_windows=(0, 4))
    m = make_model(cfg)
    params = m.init(rng)
    ids = jnp.asarray(make_batch(1, 8, vocab=64)["input_ids"])
    with pytest.raises(ValueError, match="attn_windows"):
        m.apply(params, ids)
