"""Fault-tolerance subsystem (deepspeed_tpu/robustness): retry-with-backoff,
deterministic fault injection, the checkpoint integrity chain + walk-back,
retention, data-position resume, preemption latching, and the rendezvous
torn-manifest regression.

Quick tier by design: everything here is file- and host-level (no engine
builds, no mesh compiles). The engine-integrated chaos soak lives in
tests/unit/test_chaos.py (slow tier).
"""

import errno
import json
import os
import signal

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness import integrity
from deepspeed_tpu.robustness.faults import FaultInjector, FaultSchedule
from deepspeed_tpu.robustness.preemption import PreemptionHandler
from deepspeed_tpu.robustness.retry import retry_io
from deepspeed_tpu.runtime.checkpointing import (LATEST_FILE, load_checkpoint,
                                                 resolve_load_tag,
                                                 save_checkpoint)


@pytest.fixture(autouse=True)
def _clean_robustness_state():
    rb_faults.clear()
    rb_events.clear()
    yield
    rb_faults.clear()
    rb_events.clear()


def tree(val):
    return {"w": jnp.full((4, 4), float(val)), "step": jnp.asarray(val)}


def corrupt_largest_payload(tag_dir):
    """Truncate the biggest manifest-listed file (bitrot simulation)."""
    with open(os.path.join(tag_dir, integrity.MANIFEST_FILE)) as f:
        files = json.load(f)["files"]
    victim = max(files.items(), key=lambda kv: kv[1]["size"])[0]
    p = os.path.join(tag_dir, victim)
    with open(p, "r+b") as f:
        f.truncate(max(0, os.path.getsize(p) // 2))
    return victim


# ---------------------------------------------------------------------------
# retry helper (satellite: every NVMe/AIO host-I/O call is wrapped)
# ---------------------------------------------------------------------------
class TestRetryIO:
    def test_recovers_from_transient_and_emits_event(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise OSError(errno.EIO, "flaky media")
            return "data"

        slept = []
        out = retry_io(flaky, what="test read", path="/dev/fake",
                       offset=4096, sleep=slept.append)
        assert out == "data" and calls["n"] == 3
        assert len(slept) == 2 and slept[1] > slept[0]  # backoff grows
        rec = rb_events.history("fault_recovered")[-1]
        assert rec["path"] == "/dev/fake" and rec["attempts"] == 3

    def test_terminal_error_names_file_offset_attempts(self):
        def dead():
            raise OSError(errno.EIO, "gone")

        with pytest.raises(OSError) as ei:
            retry_io(dead, what="chunk read", path="/nvme/opt_chunk_3.bin",
                     offset=12345, attempts=3, sleep=lambda s: None)
        msg = str(ei.value)
        assert "chunk read" in msg and "/nvme/opt_chunk_3.bin" in msg
        assert "@12345" in msg and "3 attempts" in msg

    def test_non_transient_not_retried(self):
        calls = {"n": 0}

        def full_disk():
            calls["n"] += 1
            raise OSError(errno.ENOSPC, "disk full")

        with pytest.raises(OSError):
            retry_io(full_disk, what="w", path="/x", sleep=lambda s: None)
        assert calls["n"] == 1  # ENOSPC doesn't un-fill within a backoff


# ---------------------------------------------------------------------------
# fault schedule / injector
# ---------------------------------------------------------------------------
class TestFaultInjection:
    def test_bad_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown kind"):
            FaultSchedule([{"kind": "meteor_strike"}])

    def test_triggerless_entry_rejected(self):
        # an entry that could never fire is a schedule that silently
        # tests nothing — reject it at validation time
        with pytest.raises(ValueError, match="needs 'at'"):
            FaultSchedule([{"kind": "io_error", "op": "ckpt_io"}])
        with pytest.raises(ValueError, match="needs 'step'"):
            FaultSchedule([{"kind": "preempt"}])

    def test_install_from_config_keeps_same_replaces_changed(self):
        from deepspeed_tpu.config.config import FaultsConfig
        cfg1 = FaultsConfig(enabled=True, seed=1, entries=[
            {"kind": "io_error", "op": "ckpt_io", "at": 0}])
        a = rb_faults.install_from_config(cfg1)
        assert rb_faults.install_from_config(cfg1) is a   # rebuild: kept
        cfg2 = FaultsConfig(enabled=True, seed=2, entries=[])
        b = rb_faults.install_from_config(cfg2)           # changed: swapped
        assert b is not a and rb_faults.active() is b
        # a manually installed injector is never replaced by config
        manual = rb_faults.install(FaultInjector(FaultSchedule([])))
        assert rb_faults.install_from_config(cfg1) is manual

    def test_io_error_window_is_deterministic(self):
        inj = FaultInjector(FaultSchedule(
            [{"kind": "io_error", "op": "nvme_read", "at": 1, "times": 2}]))
        inj.op("nvme_read", "/a")                       # index 0: clean
        for _ in range(2):                              # 1, 2: scheduled
            with pytest.raises(OSError) as ei:
                inj.op("nvme_read", "/a")
            assert ei.value.errno == errno.EIO
        inj.op("nvme_read", "/a")                       # 3: clean again
        inj.op("nvme_write", "/a")                      # other category clean
        assert len(inj.fired) == 2

    def test_injected_transient_recovered_by_retry(self):
        inj = rb_faults.install(FaultInjector(FaultSchedule(
            [{"kind": "io_error", "op": "nvme_read", "at": 0, "times": 2}])))

        def read():
            rb_faults.io_seam("nvme_read", "/nvme/c0.bin")
            return 42

        assert retry_io(read, what="chunk read", path="/nvme/c0.bin",
                        sleep=lambda s: None) == 42
        assert rb_events.history("fault_recovered")

    def test_device_fault_step_and_cull(self):
        inj = FaultInjector(FaultSchedule(
            [{"kind": "device_fault", "step": 3, "survivors": 4,
              "probes": 1}]))
        inj.step(1), inj.step(2)
        with pytest.raises(RuntimeError, match="injected device_fault"):
            inj.step(3)
        devs = list(range(8))
        assert inj.cull(devs) == [0, 1, 2, 3]   # armed: first probe shrinks
        assert inj.cull(devs) == devs           # transient blip cleared
        inj.step(3)  # once fired, the same step passes (deterministic)

    def test_clock_skew_wraps_injectable_clock(self):
        inj = FaultInjector(FaultSchedule(
            [{"kind": "clock_skew", "after": 2, "skew_s": 100.0}]))
        t = [50.0]
        clock = inj.make_clock(lambda: t[0])
        assert clock() == 50.0 and clock() == 50.0
        assert clock() == 150.0  # third read onward is skewed


# ---------------------------------------------------------------------------
# integrity chain (tentpole piece 2)
# ---------------------------------------------------------------------------
class TestIntegrityChain:
    def test_save_writes_manifest_and_marker(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "t", tree(1))
        tag = os.path.join(d, "t")
        assert integrity.is_committed(tag)
        with open(os.path.join(tag, integrity.MANIFEST_FILE)) as f:
            manifest = json.load(f)
        assert manifest["files"]  # payload hashed
        ok, reason = integrity.validate_tag(tag)
        assert ok and reason == "ok"

    def test_validate_catches_truncation_and_bitrot(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "t", tree(1))
        tag = os.path.join(d, "t")
        victim = corrupt_largest_payload(tag)
        ok, reason = integrity.validate_tag(tag)
        assert not ok and victim in reason

    def test_legacy_tag_without_integrity_is_loadable(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "t", tree(5))
        tag = os.path.join(d, "t")
        # strip the integrity files: the pre-PR-6 on-disk format
        os.remove(os.path.join(tag, integrity.COMMIT_FILE))
        os.remove(os.path.join(tag, integrity.MANIFEST_FILE))
        ok, reason = integrity.validate_tag(tag)
        assert ok and reason == "legacy"
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 5.0

    def test_retention_keeps_last_k_good_tags(self, tmp_path):
        d = str(tmp_path)
        for i in range(5):
            save_checkpoint(d, f"step{i}", tree(i), keep_last_k=2)
        tags = sorted(n for n in os.listdir(d)
                      if os.path.isdir(os.path.join(d, n)))
        assert tags == ["step3", "step4"]
        # newest still loads; latest points at it
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 4.0

    def test_retention_never_prunes_the_tag_latest_names(self, tmp_path):
        """save_latest=False can leave `latest` naming an OLDER tag than
        the one just saved — retention must protect it anyway."""
        d = str(tmp_path)
        save_checkpoint(d, "a", tree(1))          # latest -> a
        save_checkpoint(d, "b", tree(2), save_latest=False, keep_last_k=1)
        save_checkpoint(d, "c", tree(3), save_latest=False, keep_last_k=1)
        remaining = sorted(n for n in os.listdir(d)
                           if os.path.isdir(os.path.join(d, n)))
        assert "a" in remaining                   # latest's tag survives
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 1.0

    def test_overwrite_with_integrity_off_stays_loadable(self, tmp_path):
        """Re-saving a tag with integrity disabled must drop the STALE
        manifest too — otherwise the finished save reads as uncommitted
        forever and resolution silently rolls back to an older tag."""
        d = str(tmp_path)
        save_checkpoint(d, "old", tree(1))
        save_checkpoint(d, "t", tree(2))                 # integrity on
        save_checkpoint(d, "t", tree(3), write_integrity=False)
        ok, reason = integrity.validate_tag(os.path.join(d, "t"))
        assert ok and reason == "legacy"
        state, _ = load_checkpoint(d, template=tree(0))  # latest == t
        assert float(np.asarray(state["step"])) == 3.0

    def test_retention_never_counts_invalid_tags(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "a", tree(1))
        save_checkpoint(d, "b", tree(2))
        integrity.invalidate(os.path.join(d, "b"))  # torn
        save_checkpoint(d, "c", tree(3), keep_last_k=2)
        remaining = sorted(n for n in os.listdir(d)
                           if os.path.isdir(os.path.join(d, n)))
        # a + c are the last 2 GOOD tags; torn b is evidence, not capacity
        assert remaining == ["a", "b", "c"]


# ---------------------------------------------------------------------------
# load_checkpoint walk-back (acceptance: a corrupt/uncommitted latest never
# raises with tag=None)
# ---------------------------------------------------------------------------
class TestCheckpointFallback:
    def test_uncommitted_latest_falls_back(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "good", tree(1))
        save_checkpoint(d, "torn", tree(2))
        os.remove(os.path.join(d, "torn", integrity.COMMIT_FILE))
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 1.0
        ev = rb_events.history("ckpt_fallback")[-1]
        assert ev["requested"] == "torn" and ev["resolved"] == "good"
        assert "uncommitted" in ev["reason"]

    def test_truncated_payload_falls_back(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "good", tree(1))
        save_checkpoint(d, "rotten", tree(2))
        corrupt_largest_payload(os.path.join(d, "rotten"))
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 1.0
        assert rb_events.history("ckpt_fallback")

    def test_latest_pointing_at_missing_tag_falls_back(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "good", tree(7))
        with open(os.path.join(d, LATEST_FILE), "w") as f:
            f.write("never_existed")
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 7.0

    def test_commit_marker_deleted_mid_save_via_injector(self, tmp_path):
        """torn_save fault: the save 'crashes' between payload and commit
        marker. The save call raises (the process would have died); the
        NEXT load must land on the previous good tag."""
        d = str(tmp_path)
        save_checkpoint(d, "s1", tree(1))
        rb_faults.install(FaultInjector(FaultSchedule(
            [{"kind": "torn_save", "at": 0}])))
        with pytest.raises(OSError, match="torn save"):
            save_checkpoint(d, "s2", tree(2))
        assert not integrity.is_committed(os.path.join(d, "s2"))
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 1.0

    def test_corrupt_payload_injector_commits_then_fails_checksum(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "s1", tree(1))
        # indices count from injector install: s2's save is mutate-op 0
        rb_faults.install(FaultInjector(FaultSchedule(
            [{"kind": "corrupt_payload", "at": 0}])))
        save_checkpoint(d, "s2", tree(2))   # save "succeeds" (bitrot later)
        assert integrity.is_committed(os.path.join(d, "s2"))
        ok, reason = integrity.validate_tag(os.path.join(d, "s2"))
        assert not ok and "mismatch" in reason
        state, _ = load_checkpoint(d, template=tree(0))
        assert float(np.asarray(state["step"])) == 1.0

    def test_nothing_valid_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            load_checkpoint(str(tmp_path), template=tree(0))

    def test_explicit_tag_is_honored_verbatim(self, tmp_path):
        d = str(tmp_path)
        save_checkpoint(d, "a", tree(1))
        save_checkpoint(d, "b", tree(2))
        resolved, fell_back = resolve_load_tag(d, "a")
        assert resolved == "a" and not fell_back


# ---------------------------------------------------------------------------
# preemption (tentpole piece 3, host half)
# ---------------------------------------------------------------------------
class TestPreemption:
    def test_sigterm_latches_flag(self):
        with PreemptionHandler() as h:
            assert not h.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.requested and h.received == signal.SIGTERM
            h.reset()
            assert not h.requested
        # restored: the default handler is back (don't send SIGTERM now!)
        assert signal.getsignal(signal.SIGTERM) is not h._on_signal

    def test_injector_preempt_delivers_real_sigterm(self):
        inj = FaultInjector(FaultSchedule([{"kind": "preempt", "step": 2}]))
        with PreemptionHandler() as h:
            inj.step(1)
            assert not h.requested
            inj.step(2)      # delivers SIGTERM to this process
            assert h.requested


# ---------------------------------------------------------------------------
# rendezvous: torn NEWEST manifest regression (satellite)
# ---------------------------------------------------------------------------
class TestRendezvousTornManifest:
    def test_torn_newest_manifest_falls_back_not_none(self, tmp_path):
        """A torn newest gen file must NOT erase history: current_generation
        falls back to the next-newest readable manifest, so the leader's
        next publish is gen N+1, never a gen-0 rewrite."""
        from deepspeed_tpu.elasticity import FileRendezvous
        t = [100.0]
        a = FileRendezvous(str(tmp_path), "host-a", dead_after_s=10.0,
                           clock=lambda: t[0])
        a.heartbeat()
        a.propose_generation()           # gen 0
        a.propose_generation()           # gen 1
        # gen 1's file is torn in place (crashed writer, partial flush)
        (tmp_path / "gen_00000001.json").write_text('{"genera')
        cur = a.current_generation()
        assert cur is not None and cur["generation"] == 0
        # and the next publish continues history instead of rewriting it
        m = a.propose_generation()
        assert m["generation"] == 1
        assert a.current_generation()["generation"] == 1

    def test_clock_skew_fault_ages_out_heartbeats(self, tmp_path):
        """Injected clock skew = heartbeat loss: the skewed observer sees
        its peer's heartbeat age past dead_after_s and re-forms."""
        from deepspeed_tpu.elasticity import FileRendezvous
        inj = FaultInjector(FaultSchedule(
            [{"kind": "clock_skew", "after": 3, "skew_s": 60.0}]))
        t = [100.0]
        a = FileRendezvous(str(tmp_path), "host-a", dead_after_s=10.0,
                           clock=inj.make_clock(lambda: t[0]))
        b = FileRendezvous(str(tmp_path), "host-b", dead_after_s=10.0,
                           clock=lambda: t[0])
        a.heartbeat(); b.heartbeat()               # a's clock: read 1
        assert a.live_hosts() == ["host-a", "host-b"]   # read 2: unskewed
        a.heartbeat()                              # read 3: last unskewed ts
        a.heartbeat()                              # read 4: SKEWED ts=160
        # a's view is now 60s ahead: b's ts-100 heartbeat looks dead while
        # a's own (written with the skewed ts) still looks live
        assert a.live_hosts() == ["host-a"]
        assert a.is_leader()


# ---------------------------------------------------------------------------
# data-pipeline position (satellite): resume neither replays nor skips
# ---------------------------------------------------------------------------
class TestDataPositionResume:
    def _loader(self, **kw):
        from deepspeed_tpu.runtime.dataloader import DataLoader
        data = [{"x": np.full((2,), i, np.int32)} for i in range(32)]
        return DataLoader(data, batch_size=4, shuffle=True, seed=7, **kw)

    @staticmethod
    def _ids(batch):
        return batch["x"][:, 0].tolist()

    def test_state_dict_resume_is_exact(self):
        ref = self._loader()
        full = [self._ids(b) for b in ref]          # the uninterrupted epoch
        run = self._loader()
        it = iter(run)
        consumed = [self._ids(next(it)) for _ in range(3)]
        sd = run.state_dict()
        assert sd == {"epoch": 0, "pos": 3, "seed": 7}
        resumed = self._loader()                    # a fresh process
        resumed.load_state_dict(sd)
        rest = [self._ids(b) for b in resumed]
        assert consumed + rest == full              # no replay, no skip

    def test_resume_across_epoch_boundary(self):
        from deepspeed_tpu.runtime.dataloader import RepeatingLoader
        ref = RepeatingLoader(self._loader())
        full = [self._ids(next(ref)) for _ in range(20)]   # spans 2+ epochs
        run = RepeatingLoader(self._loader())
        consumed = [self._ids(next(run)) for _ in range(11)]  # epoch 1, pos 3
        sd = run.state_dict()
        assert sd["epoch"] == 1 and sd["pos"] == 3
        resumed = RepeatingLoader(self._loader())
        resumed.load_state_dict(sd)
        rest = [self._ids(next(resumed)) for _ in range(9)]
        assert consumed + rest == full

    def test_set_epoch_resets_position(self):
        run = self._loader()
        it = iter(run)
        next(it)
        run.set_epoch(1)
        assert run.state_dict()["pos"] == 0


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------
class TestRobustnessConfig:
    def test_fault_entries_validated_at_config_load(self):
        from deepspeed_tpu.config.config import Config
        from deepspeed_tpu.config.config_utils import ConfigError
        with pytest.raises((ConfigError, ValueError), match="unknown kind"):
            Config.load({"robustness": {"faults": {
                "enabled": True, "entries": [{"kind": "nope"}]}}})
        cfg = Config.load({"robustness": {"faults": {
            "enabled": True, "seed": 3,
            "entries": [{"kind": "io_error", "op": "nvme_read", "at": 0}]}}})
        assert cfg.robustness.faults.seed == 3

    def test_checkpoint_integrity_keys(self):
        from deepspeed_tpu.config.config import Config
        cfg = Config.load({"checkpoint": {"keep_last_k": 3,
                                          "integrity_checksums": False}})
        assert cfg.checkpoint.keep_last_k == 3
        assert cfg.checkpoint.integrity and not cfg.checkpoint.integrity_checksums

    def test_events_drain_and_history(self):
        rb_events.emit("ckpt_fallback", requested="a", resolved="b",
                       reason="test")
        drained = rb_events.drain()
        assert drained[-1]["type"] == "ckpt_fallback"
        assert rb_events.drain() == []                   # empty after drain
        assert rb_events.history("ckpt_fallback")        # history persists
