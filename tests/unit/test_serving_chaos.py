"""Serving chaos soak: a mixed continuous-batching load under every
serving-seam fault must end BIT-IDENTICAL to the fault-free run.

The schedule exercises the four injected conditions the reliability tier
exists for, in one soak:

  * ``decode_dispatch`` (fail)  — a failed quantum dispatch: recovery
    preempts every running request, rebuilds the pool, re-prefills from
    host cursors and retries the round;
  * ``pool_exhaust``            — a 2-round allocator exhaustion storm: the
    scheduler queues/preempts through it, nothing OOMs, nothing is lost;
  * ``backend_fault``           — a Pallas kernel failure mid-serve: the
    engine degrades to the XLA gather backend (``backend_degraded``) and
    keeps every sequence's tokens identical (the gather is the same math
    the kernel-parity tests pin);
  * ``decode_dispatch`` (hang)  — a hung dispatch: the round watchdog times
    it out and the same recovery path heals it;
  * ``preempt`` (round-keyed)   — a real SIGTERM: the engine drains through
    the integrity chain and a RESTARTED engine resumes the in-flight
    requests with byte-identical continuations.

Shed and deadline-miss events ride along via two canary requests (outside
the compared set), so the telemetry JSONL ends up carrying the full event
schema. Slow tier: three engine builds on interpret-mode Pallas. Runs
under tests/run_slow.sh with its own budget (SERVING_CHAOS_BUDGET).

ISSUE 12 extends the soak with the latency tier ARMED: the same fault
schedule runs with the copy-on-write prefix cache, token-budget chunked
prefill and speculative decoding all on, over a load where most prompts
share a prefix — so recoveries rebuild pools with refcounted tables in
play (the cache's references are cleared with the pool), the SIGTERM
drain serializes mid-chunk prefills and preemption re-prefills re-match
the cache on resume. The acceptance bar is the same and stricter: outputs
bit-identical to the PLAIN fault-free engine (latency features and
faults both invisible in the token stream).
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.scheduler import AdmissionRejected
from deepspeed_tpu.models import TransformerConfig, make_model
from deepspeed_tpu.robustness import events as rb_events
from deepspeed_tpu.robustness import faults as rb_faults
from deepspeed_tpu.robustness.faults import FaultInjector, FaultSchedule
from deepspeed_tpu.robustness.preemption import Preempted, PreemptionHandler

pytestmark = pytest.mark.slow

N_REQUESTS = 32


@pytest.fixture(autouse=True)
def _clean_robustness_state():
    rb_faults.clear()
    rb_events.clear()
    yield
    rb_faults.clear()
    rb_events.clear()


def _model():
    # head_dim 64: paged-kernel eligible, so the soak can run FORCED pallas
    # and the backend_fault degradation ladder (pallas -> XLA gather) is
    # exercised for real (interpret mode on CPU)
    return make_model(TransformerConfig(
        vocab_size=128, hidden_size=256, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=256, position_type="rotary",
        activation="silu_glu", norm_type="rmsnorm", tie_embeddings=False,
        dtype=jnp.float32, attention_impl="xla"))


def _load():
    rng = np.random.default_rng(7)
    return [(rng.integers(0, 128, size=(int(n),)).astype(np.int32), int(k))
            for n, k in zip(rng.integers(5, 40, N_REQUESTS),
                            rng.integers(8, 15, N_REQUESTS))]


def _serving(model, params, jsonl=None, **kw):
    d = dict(max_seqs=4, block_size=16, max_model_len=128,
             decode_quantum=2, prompt_bucket=16, num_blocks=20,
             decode_backend="pallas", telemetry_jsonl=jsonl)
    d.update(kw)
    return deepspeed_tpu.init_serving(model, config={}, serving=d,
                                      dtype=jnp.float32,
                                      params=jax.device_get(params))


class TestServingChaosSoak:
    def test_soak_bit_identical_to_fault_free(self, tmp_path):
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        reqs = _load()

        # ---- fault-free baseline (same forced-pallas config) ----------
        srv = _serving(model, params)
        base = srv.run(list(reqs))
        assert len(base) == N_REQUESTS
        del srv

        # ---- chaos run ------------------------------------------------
        # round-indexed schedule (see module docstring); the SIGTERM at
        # round 16 drains mid-load and a fresh engine resumes
        inj = rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "decode_dispatch", "at": 2},
            {"kind": "pool_exhaust", "at": 5, "times": 2},
            {"kind": "backend_fault", "at": 8},
            {"kind": "decode_dispatch", "at": 12, "mode": "hang",
             "hang_s": 2.5},
            {"kind": "preempt", "round": 16},
        ], seed=3)))
        rb_events.clear()
        jsonl = str(tmp_path / "tel" / "serving_events.jsonl")
        drain_dir = str(tmp_path / "drain")
        handler = PreemptionHandler().install()
        outs, rounds, engines = {}, 0, []
        try:
            srv1 = _serving(model, params, jsonl=jsonl,
                            dispatch_timeout_s=1.0)
            engines.append(srv1)
            srv1.attach_preemption(handler, drain_dir)
            for p, k in reqs:
                srv1.add_request(p, k)
            resumed = False
            srv_cur = srv1
            while not srv_cur.scheduler.done:
                try:
                    for r in srv_cur.step():
                        outs[r.rid] = r.output
                    rounds += 1
                except Preempted:
                    assert not resumed, "preempted twice"
                    resumed = True
                    # the drained engine checkpointed through the
                    # integrity chain; a FRESH engine resumes the work
                    handler.reset()
                    srv2 = _serving(model, params, jsonl=jsonl,
                                    dispatch_timeout_s=1.0)
                    engines.append(srv2)
                    rids = srv2.resume(drain_dir)
                    assert rids, "nothing was in flight at the drain"
                    # canaries (outside the compared set): a shed and a
                    # deadline miss, so those events reach the JSONL too
                    srv2.scheduler.max_queue = 0
                    with pytest.raises(AdmissionRejected):
                        srv2.add_request(np.arange(4, dtype=np.int32), 4)
                    srv2.scheduler.max_queue = None
                    srv2.add_request(np.arange(4, dtype=np.int32), 4,
                                     ttft_deadline_ms=1e-3)
                    srv_cur = srv2
            assert resumed, "the SIGTERM preemption never fired"
        finally:
            handler.restore()
            rb_faults.clear()
        for srv in engines:          # requests finished before the drain
            for r in srv._finished:
                outs.setdefault(r.rid, r.output)

        # every scheduled fault actually fired
        fired = {f["kind"] for f in inj.fired}
        assert fired == {"decode_dispatch", "pool_exhaust", "backend_fault",
                         "preempt"}, fired
        modes = {f.get("mode") for f in inj.fired
                 if f["kind"] == "decode_dispatch"}
        assert modes == {"fail", "hang"}          # both dispatch shapes

        # degradation happened mid-serve and was evented; recoveries ran;
        # the soak is a REAL 40-round mixed load
        assert srv1.decode_backend == "xla"       # pallas -> gather ladder
        assert srv1.stats()["degraded"] == 1.0
        st = [e.stats() for e in engines]
        assert sum(s["recoveries"] for s in st) >= 3   # fail + hang + fault
        assert rounds >= 40, rounds

        # the canaries produced shed + deadline evidence without touching
        # the compared set
        assert srv_cur.stats()["shed"] == 1.0
        assert srv_cur.stats()["deadline_misses"] == 1.0

        # ---- the acceptance bar: BIT-IDENTICAL outputs ----------------
        assert set(outs) >= set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged under chaos")

        # ---- events visible in the telemetry JSONL --------------------
        types = set()
        for p in glob.glob(os.path.join(os.path.dirname(jsonl), "*")):
            with open(p) as f:
                for line in f:
                    try:
                        types.add(json.loads(line).get("type"))
                    except ValueError:
                        pass
        assert {"fault_injected", "serving_recovered", "backend_degraded",
                "serving_drained", "serving_resumed", "request_shed",
                "deadline_miss"} <= types, types


def _shared_load(n=24):
    """Mostly-shared-prefix mix: ~2/3 of the requests extend one long
    system prompt (the prefix cache's target traffic), the rest are
    unique — so the soak exercises hits, forks AND cold paths."""
    rng = np.random.default_rng(17)
    shared = rng.integers(0, 128, size=(34,)).astype(np.int32)
    reqs = []
    for i in range(n):
        if i % 3 < 2:
            p = np.concatenate([shared, rng.integers(0, 128, size=(
                int(rng.integers(2, 8)),)).astype(np.int32)])
        else:
            p = rng.integers(0, 128, size=(
                int(rng.integers(5, 30)),)).astype(np.int32)
        reqs.append((p, int(rng.integers(8, 14))))
    return reqs


class TestLatencyTierChaosSoak:
    def test_soak_with_prefix_cache_and_speculation_armed(self, tmp_path):
        """ISSUE 12: the fault schedule replayed with CoW prefix cache +
        chunked prefill + speculation armed ends bit-identical to the
        PLAIN fault-free run — shared (refcounted) block tables survive
        recovery pool-rebuilds, drain/resume and preemption re-prefill."""
        model = _model()
        params = model.init(jax.random.PRNGKey(0))
        reqs = _shared_load()
        latency = dict(enable_prefix_cache=True, prefill_token_budget=48,
                       spec_tokens=2, decode_backend="auto")

        # plain fault-free baseline: no latency features, no faults — the
        # strictest possible reference (greedy parity makes the features
        # invisible; the soak proves the faults are too)
        srv = _serving(model, params, decode_backend="auto")
        base = srv.run(list(reqs))
        del srv

        inj = rb_faults.install(FaultInjector(FaultSchedule([
            {"kind": "decode_dispatch", "at": 2},
            {"kind": "pool_exhaust", "at": 5, "times": 2},
            {"kind": "decode_dispatch", "at": 9},
            {"kind": "preempt", "round": 14},
        ], seed=5)))
        rb_events.clear()
        drain_dir = str(tmp_path / "drain_lat")
        handler = PreemptionHandler().install()
        outs, engines = {}, []
        try:
            srv1 = _serving(model, params, **latency)
            engines.append(srv1)
            srv1.attach_preemption(handler, drain_dir)
            for p, k in reqs:
                srv1.add_request(p, k)
            resumed = False
            srv_cur = srv1
            while not srv_cur.scheduler.done:
                try:
                    for r in srv_cur.step():
                        outs[r.rid] = r.output
                except Preempted:
                    assert not resumed, "preempted twice"
                    resumed = True
                    handler.reset()
                    srv2 = _serving(model, params, **latency)
                    engines.append(srv2)
                    rids = srv2.resume(drain_dir)
                    assert rids, "nothing was in flight at the drain"
                    srv_cur = srv2
            assert resumed, "the SIGTERM preemption never fired"
        finally:
            handler.restore()
            rb_faults.clear()
        for srv in engines:
            for r in srv._finished:
                outs.setdefault(r.rid, r.output)

        fired = {f["kind"] for f in inj.fired}
        assert fired == {"decode_dispatch", "pool_exhaust", "preempt"}, \
            fired
        # the latency tier actually engaged: cache hits with forks on the
        # shared prompts, chunked prefills, speculation verify steps —
        # across both engines (the resumed one re-prefills via ITS cache)
        st = [e.stats() for e in engines]
        assert sum(s.get("prefix_hits", 0) for s in st) >= 6
        assert sum(s.get("cow_forks", 0) for s in st) >= 1
        assert sum(s.get("spec_steps", 0) for s in st) > 0
        assert sum(s.get("prefill_chunks", 0) for s in st) >= 1
        assert sum(s["recoveries"] for s in st) >= 2

        # the acceptance bar: BIT-IDENTICAL to the plain engine
        assert set(outs) >= set(base)
        for rid in base:
            np.testing.assert_array_equal(
                base[rid], outs[rid],
                err_msg=f"request {rid} diverged under latency-tier chaos")
        # refcount hygiene after the storm: every surviving engine's held
        # blocks are exactly its cache's (nothing leaked through the
        # recoveries and the drain)
        for e in engines:
            if e.scheduler.done:
                assert e.allocator.used_blocks == \
                    e._prefix_cache.held_blocks
