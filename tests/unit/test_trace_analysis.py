"""Perf doctor: trace parsing, stall attribution, doctor gate, capture.

The quick tier runs against the checked-in fixture
(tests/fixtures/doctor_trace.json + .hlo.txt — a hand-built 9.5 ms step
with one op per bucket and known interval overlaps); the slow tier drives
a REAL ``jax.profiler`` capture through a tiny engine and pins bit-for-bit
numerics parity with capture on vs off (same methodology as the telemetry
on/off parity suite: 20 fp16 steps with a forced overflow at step 7).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.profiling import trace_analysis as ta
from deepspeed_tpu.profiling import doctor

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "..", "fixtures")
TRACE_PATH = os.path.join(FIXTURE_DIR, "doctor_trace.json")
HLO_PATH = os.path.join(FIXTURE_DIR, "doctor_trace.hlo.txt")


def fixture_trace():
    with open(TRACE_PATH) as f:
        return json.load(f)


def fixture_scope_map():
    with open(HLO_PATH) as f:
        return ta.parse_hlo_scopes(f.read())


# --------------------------------------------------------------------------
# parsing + classification
# --------------------------------------------------------------------------

class TestParsing:
    def test_hlo_scope_map(self):
        m = fixture_scope_map()
        assert m["dot.1"] == \
            "jit(train_step)/grads/layers/mlp/dot_general"
        assert m["fusion.5"].endswith("layers/attn/dot_general")
        assert "all-reduce.3" in m and "tanh.2" in m

    def test_normalize_scope_unwraps_autodiff(self):
        parts, bwd = ta.normalize_scope(
            "jit(train_step)/grads/transpose(jvp(layers))/mlp/tanh")
        assert parts == ("grads", "layers", "mlp", "tanh")
        assert bwd
        parts, bwd = ta.normalize_scope(
            "jit(train_step)/grads/layers/attn/dot_general")
        assert parts == ("grads", "layers", "attn", "dot_general")
        assert not bwd

    def test_bucket_classification(self):
        assert ta.bucket_of("dot.7") == "matmul"
        assert ta.bucket_of("all-reduce.3") == "collective"
        assert ta.bucket_of("all-gather-start.1") == "collective"
        assert ta.bucket_of("infeed.4") == "host-stall"
        assert ta.bucket_of("tanh.5") == "elementwise"
        # scope context promotes fusions into the attention bucket
        assert ta.bucket_of("fusion.9", "grads/layers/attn/dot") \
            == "attention"

    def test_device_events_filters_noise(self):
        evs = ta.device_events(fixture_trace())
        assert len(evs) == 5
        assert all("hlo_op" in (e.get("args") or {}) for e in evs)

    def test_interval_arithmetic(self):
        merged = ta.merge_intervals([(0, 4), (4, 6), (6, 7), (6.5, 8.5),
                                     (9, 9.5)])
        assert merged == [(0, 8.5), (9, 9.5)]
        assert ta.interval_total(merged) == pytest.approx(9.0)
        exposed = ta.subtract_intervals([(6.5, 8.5)], [(0, 7)])
        assert exposed == [(7, 8.5)]


# --------------------------------------------------------------------------
# attribution on the fixture (known totals)
# --------------------------------------------------------------------------

class TestAttribution:
    def attr(self):
        return ta.attribute(fixture_trace(), fixture_scope_map())

    def test_bucket_totals(self):
        a = self.attr()
        ms = {b: s["ms"] for b, s in a.buckets.items()}
        assert ms["matmul"] == pytest.approx(4.0)
        assert ms["attention"] == pytest.approx(2.0)
        assert ms["elementwise"] == pytest.approx(1.0)
        assert ms["collective"] == pytest.approx(2.0)
        assert ms["host-stall"] == pytest.approx(0.5)
        assert ms["dispatch-gap"] == pytest.approx(0.5)

    def test_span_busy_and_gap(self):
        a = self.attr()
        assert a.step_span_ms == pytest.approx(9.5)
        assert a.device_busy_ms == pytest.approx(9.0)

    def test_exposed_comm_is_interval_true(self):
        """The 2 ms all-reduce overlaps compute for its first 0.5 ms only
        (tanh ends at 7 ms): measured exposure is 1.5 ms, NOT the full 2."""
        a = self.attr()
        assert a.exposed_comm_ms == pytest.approx(1.5)

    def test_fwd_bwd_split(self):
        a = self.attr()
        assert a.bwd_ms == pytest.approx(1.0)   # the transpose(jvp) tanh
        assert a.fwd_ms == pytest.approx(8.5)

    def test_by_scope_aggregation(self):
        a = self.attr()
        assert a.by_scope_ms["grads/layers/mlp"] == pytest.approx(4.0)
        assert a.by_scope_ms["grads/layers/attn"] == pytest.approx(2.0)
        assert a.by_scope_ms["grads/layers/mlp[bwd]"] == pytest.approx(1.0)
        assert a.by_scope_ms["grads/grad_sync"] == pytest.approx(2.0)

    def test_top2_ranking(self):
        """Collective ranks by its EXPOSED 1.5 ms (not total 2 ms), then
        elementwise; compute-bound matmul/attention never rank as stalls."""
        top = ta.stall_top2(self.attr())
        assert [t["bucket"] for t in top] == ["collective", "elementwise"]
        assert top[0]["ms"] == pytest.approx(1.5)
        assert top[0]["bound"] == "exposed-comm"
        assert top[1]["ms"] == pytest.approx(1.0)
        for t in top:
            assert 0 < t["fraction"] < 1

    def test_collective_census_join(self):
        a = self.attr()
        joined = ta.join_census(a, {"all-reduce": {"count": 1,
                                                   "bytes": 1 << 20}})
        (row,) = joined
        assert row["kind"] == "all-reduce"
        assert row["measured_ms"] == pytest.approx(2.0)
        assert row["census_bytes"] == 1 << 20

    def test_steps_normalization(self):
        a2 = ta.attribute(fixture_trace(), fixture_scope_map(), steps=2)
        assert a2.buckets["matmul"]["ms"] == pytest.approx(2.0)
        assert a2.step_span_ms == pytest.approx(9.5 / 2)


# --------------------------------------------------------------------------
# doctor gate + CLI
# --------------------------------------------------------------------------

class TestDoctor:
    def test_exposed_collective_gate_fires(self):
        d = doctor.diagnose(fixture_trace(),
                            open(HLO_PATH).read())
        report = doctor.gate(d)   # 1.5/9.5 = 15.8% > the 15% budget
        assert not report.ok
        assert report.findings[0].rule == "exposed-collective-measured"

    def test_corpus_entry_fires(self):
        report = doctor.run_corpus_entry()
        assert not report.ok
        rules = {f.rule for f in report.findings}
        assert "exposed-collective-measured" in rules

    def test_corpus_registered_in_analysis_runner(self):
        from deepspeed_tpu.analysis.corpus import CORPUS, run_corpus
        assert "exposed-collective-trace" in CORPUS
        assert not run_corpus("exposed-collective-trace").ok

    def test_divergence_warning(self):
        d = doctor.diagnose(fixture_trace(), open(HLO_PATH).read(),
                            modeled_exposed_comm_ms=0.2)
        assert d["exposed_comm_divergence"] > 0.25
        report = doctor.gate(d, max_exposed_fraction=0.5)
        warn = [f for f in report.findings
                if f.rule == "modeled-measured-divergence"]
        assert warn and warn[0].severity == "warning"
        assert report.ok   # warning-only: the gate stays green

    def test_baseline_regression_gate(self):
        d = doctor.diagnose(fixture_trace(), open(HLO_PATH).read())
        base = doctor.baseline_dict(d)
        # same diagnosis vs its own baseline: no regression
        assert doctor.gate(d, baseline=base,
                           max_exposed_fraction=0.5).ok
        # grow the elementwise bucket past rel+abs tolerance
        worse = json.loads(json.dumps(d))
        worse["buckets"]["elementwise"]["fraction"] += 0.10
        rep = doctor.gate(worse, baseline=base, max_exposed_fraction=0.5)
        assert not rep.ok
        assert rep.findings[0].rule == "stall-regression"
        assert rep.findings[0].ident == "elementwise"

    def test_cli_roundtrip(self, tmp_path):
        out = tmp_path / "diag.json"
        base = tmp_path / "base.json"
        # write-baseline accepts the state and exits 0
        rc = doctor.main(["--trace", TRACE_PATH, "--hlo", HLO_PATH,
                          "--max-exposed-frac", "0.5",
                          "--write-baseline", str(base)])
        assert rc == 0 and base.exists()
        # gated rerun against the fresh baseline passes, JSON lands
        rc = doctor.main(["--trace", TRACE_PATH, "--hlo", HLO_PATH,
                          "--max-exposed-frac", "0.5",
                          "--baseline", str(base), "--json", str(out)])
        assert rc == 0
        payload = json.loads(out.read_text())
        assert payload["ok"] and payload["stall_top2"]
        # the default exposed budget (15%) gates this fixture
        rc = doctor.main(["--trace", TRACE_PATH, "--hlo", HLO_PATH])
        assert rc == 1

    def test_stall_fields_shape(self):
        d = doctor.diagnose(fixture_trace(), open(HLO_PATH).read())
        f = doctor.stall_fields(d, "seq2048")
        (top,) = [f["stall_top2_seq2048"]]
        assert len(top) == 2
        assert set(top[0]) == {"bucket", "ms", "fraction"}


# --------------------------------------------------------------------------
# artifact rotation
# --------------------------------------------------------------------------

class TestRotation:
    def test_rotation_caps_count_and_bytes(self, tmp_path):
        from deepspeed_tpu.profiling.capture import rotate_artifacts
        import time as _time
        for i in range(6):
            p = tmp_path / f"trace_t{i}.json.gz"
            p.write_bytes(b"x" * 100)
            _time.sleep(0.01)
        removed = rotate_artifacts(str(tmp_path), max_files=3)
        assert len(removed) == 3
        left = sorted(os.path.basename(p) for p in
                      (str(tmp_path / f) for f in os.listdir(tmp_path)))
        assert left == ["trace_t3.json.gz", "trace_t4.json.gz",
                        "trace_t5.json.gz"]
        removed = rotate_artifacts(str(tmp_path), max_files=10,
                                   max_total_bytes=250)
        assert len(removed) == 1   # 3 x 100 bytes > 250: oldest goes

    def test_rotation_removes_trace_hlo_pairs_together(self, tmp_path):
        """One capture = a .json.gz + .hlo.txt.gz pair: rotation must never
        orphan the hlo half of an evicted trace."""
        from deepspeed_tpu.profiling.capture import rotate_artifacts
        import time as _time
        for i in range(3):
            (tmp_path / f"trace_p{i}.json.gz").write_bytes(b"x" * 50)
            (tmp_path / f"trace_p{i}.hlo.txt.gz").write_bytes(b"y" * 50)
            _time.sleep(0.01)
        removed = rotate_artifacts(str(tmp_path), max_files=2)
        assert sorted(os.path.basename(p) for p in removed) == \
            ["trace_p0.hlo.txt.gz", "trace_p0.json.gz"]
        left = sorted(os.listdir(tmp_path))
        assert len(left) == 4 and all("p0" not in f for f in left)


# --------------------------------------------------------------------------
# real capture (slow tier: drives jax.profiler on this backend)
# --------------------------------------------------------------------------

def _tiny_engine(**cfg_overrides):
    from deepspeed_tpu.models import TransformerConfig, make_model
    cfg = TransformerConfig(vocab_size=128, hidden_size=32, num_layers=2,
                            num_heads=2, max_seq_len=64,
                            dtype=jnp.float32, attention_impl="xla")
    model = make_model(cfg, name="trace-test")
    conf = {"train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": False},
            "steps_per_print": 1000000}
    conf.update(cfg_overrides)
    engine, *_ = deepspeed_tpu.initialize(model=model, config=conf)
    return engine


@pytest.mark.slow
class TestRealCapture:
    def test_capture_writes_artifact_and_attributes(self, tmp_path):
        from deepspeed_tpu.profiling.capture import capture_traced_step
        engine = _tiny_engine()
        rng = np.random.default_rng(0)
        b = {"input_ids": rng.integers(0, 128, (8, 64), dtype=np.int32)}
        res = capture_traced_step(engine, b, str(tmp_path), tag="t",
                                  steps=2)
        assert res is not None
        assert os.path.exists(res.artifact_path)
        # artifact round-trips through the doctor CLI
        rc = doctor.main(["--trace", res.artifact_path,
                          "--max-exposed-frac", "1.0"])
        assert rc == 0
        a = res.attribution()
        assert a.total_ops > 0 and a.step_span_ms > 0
        assert a.joined_ops > 0          # HLO metadata join found scopes
        assert "matmul" in a.buckets
        # the engine named scopes made it into the measured table
        assert any(k.startswith("grads") for k in a.by_scope_ms)
        assert any(k.startswith("optimizer") for k in a.by_scope_ms)

    def test_measured_module_profile(self, tmp_path):
        from deepspeed_tpu.profiling.flops_profiler import (
            measured_module_profile)
        engine = _tiny_engine()
        rng = np.random.default_rng(0)
        b = {"input_ids": rng.integers(0, 128, (8, 64), dtype=np.int32)}
        prof = measured_module_profile(engine, b, out_dir=str(tmp_path))
        assert prof is not None
        assert prof["modules"] and prof["step_span_ms"] > 0
        # at least one row joined measured latency with analytic flops
        assert any("achieved_tflops" in r for r in prof["modules"])

    def test_capture_changes_no_numerics(self):
        """Bit-for-bit: 20 fp16 steps with a forced overflow at step 7,
        with a profiler capture window + attribution around steps 5-8 —
        same final param bits as the uninstrumented run (the telemetry
        parity methodology; capture must observe, never perturb)."""
        from tests.unit.test_telemetry import (ToyLinear, fp16_cfg,
                                               overflow_batches, params_bits)
        from deepspeed_tpu.profiling.capture import (find_trace_json,
                                                     trace_window)
        import tempfile
        batches = overflow_batches()

        ref, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                           config=fp16_cfg())
        for b in batches:
            ref.train_batch(b)

        cap, *_ = deepspeed_tpu.initialize(model=ToyLinear(),
                                           config=fp16_cfg())
        raw = tempfile.mkdtemp(prefix="dstpu-parity-trace-")
        for i, b in enumerate(batches[:5]):
            cap.train_batch(b)
        with trace_window(raw):
            for b in batches[5:8]:
                cap.train_batch(b)
            jax.block_until_ready(cap.state)
        for b in batches[8:]:
            cap.train_batch(b)

        assert ref.global_steps == cap.global_steps == 20
        assert ref.skipped_steps == cap.skipped_steps == 1
        np.testing.assert_array_equal(params_bits(ref), params_bits(cap))
        # and the captured window is analyzable
        path = find_trace_json(raw)
        if path is not None:   # platform produced a host trace
            a = ta.attribute(ta.load_trace(path), steps=3)
            assert a.total_ops > 0
