"""Mesh planner + topology tests (reference: tests/unit/runtime/pipe topology
tests + utils/groups semantics)."""

import numpy as np
import pytest

from deepspeed_tpu.config import Config
from deepspeed_tpu.parallel import MeshPlan, Topology, build_mesh, plan_from_config


def test_plan_pure_dp():
    cfg = Config.load({})
    plan = plan_from_config(cfg, 8)
    assert plan.data == 8 and plan.fsdp == 1
    assert plan.dp_world_size == 8


def test_plan_zero3_uses_fsdp():
    cfg = Config.load({"zero_optimization": {"stage": 3}})
    plan = plan_from_config(cfg, 8)
    assert plan.fsdp == 8 and plan.data == 1


def test_plan_tp():
    cfg = Config.load({"tensor_parallel": {"size": 2}})
    plan = plan_from_config(cfg, 8)
    assert plan.tensor == 2 and plan.data == 4


def test_plan_pp_tp():
    cfg = Config.load({"tensor_parallel": {"size": 2}, "pipeline": {"stages": 2}})
    plan = plan_from_config(cfg, 8)
    assert plan.pipe == 2 and plan.tensor == 2 and plan.data == 2


def test_plan_explicit_mesh():
    cfg = Config.load({"mesh": {"axes": {"data": 2, "tensor": 4}}})
    plan = plan_from_config(cfg, 8)
    assert plan.data == 2 and plan.tensor == 4


def test_plan_indivisible_raises():
    cfg = Config.load({"tensor_parallel": {"size": 3}})
    with pytest.raises(ValueError):
        plan_from_config(cfg, 8)


def test_build_mesh(devices8):
    plan = MeshPlan(data=4, tensor=2)
    mesh = build_mesh(plan)
    assert mesh.shape["data"] == 4
    assert mesh.shape["tensor"] == 2
    assert mesh.shape["pipe"] == 1


def test_topology_grid():
    topo = Topology(MeshPlan(pipe=2, data=2, tensor=2))
    assert topo.world_size() == 8
    # rank layout: pipe-major (AXIS_ORDER)
    assert topo.get_rank(pipe=0, data=0, tensor=0) == 0
    assert topo.get_rank(pipe=1, data=0, tensor=0) == 4
    coord = topo.get_coord(5)
    assert coord["pipe"] == 1
    lists = topo.get_axis_comm_lists("tensor")
    assert [0, 1] in lists
    assert topo.filter_match(pipe=1) == [4, 5, 6, 7]
