"""Test harness: single-process 8-virtual-device CPU mesh.

Reference test strategy (SURVEY §4): the reference spawns N torch processes
per test (tests/unit/common.py DistributedExec). The TPU-idiomatic equivalent
is one process with XLA_FLAGS=--xla_force_host_platform_device_count=8 — the
SPMD partitioner behaves identically to a real 8-chip slice, minus the wire.

Env vars MUST be set before jax imports, hence module level.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at a TPU
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DSTPU_LOG_LEVEL", "warning")

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# the image's sitecustomize imports jax before conftest runs, so the env vars
# above may be too late — force the platform through the live config instead.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


def make_batch(batch_size: int, seq_len: int, vocab: int = 256, seed: int = 0):
    r = np.random.default_rng(seed)
    return {"input_ids": r.integers(0, vocab, size=(batch_size, seq_len), dtype=np.int32)}


@pytest.fixture()
def tiny_model():
    from deepspeed_tpu.models import TransformerConfig, make_model
    import jax.numpy as jnp
    cfg = TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
        max_seq_len=128, dtype=jnp.float32, attention_impl="xla")
    return make_model(cfg, name="tiny")
