"""Test harness: single-process 8-virtual-device CPU mesh.

Reference test strategy (SURVEY §4): the reference spawns N torch processes
per test (tests/unit/common.py DistributedExec). The TPU-idiomatic equivalent
is one process with XLA_FLAGS=--xla_force_host_platform_device_count=8 — the
SPMD partitioner behaves identically to a real 8-chip slice, minus the wire.

Env vars MUST be set before jax imports, hence module level.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at a TPU
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DSTPU_LOG_LEVEL", "warning")

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# the image's sitecustomize imports jax before conftest runs, so the env vars
# above may be too late — force the platform through the live config instead.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# NOTE on the persistent XLA compilation cache: tried (it cut warm runs
# ~4x) and REVERTED — on this jaxlib/CPU combination, re-loading cached
# executables for the donated+sharded engine train steps SIGABRTs inside
# XLA on the first value fetch (reproduced with TestZeroStages: cold run
# passes, warm run aborts; JAX_PERSISTENT_CACHE_ENABLE_XLA_CACHES=none
# does not help). Opt in explicitly if your jaxlib is newer:
if os.environ.get("DSTPU_TEST_COMPILE_CACHE"):
    _cache_dir = os.path.join(
        os.environ.get("DSTPU_CACHE_DIR")
        or os.path.join(os.environ.get("XDG_CACHE_HOME",
                                       os.path.expanduser("~/.cache")),
                        "deepspeed_tpu"),
        "jax-test-cache")
    try:  # an unwritable cache location must not error the whole session
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except OSError:
        pass

_t_session_start = None


def pytest_configure(config):
    # quick tier (-m "not slow"): tests are COMPILE-bound on this 1-core box
    # and correctness-tolerance based, so trade codegen quality for compile
    # time (~30% wall cut measured). The full tier keeps default
    # optimization — the heavy numerical-parity suites run with production
    # codegen. This hook runs after CLI parsing (exact markexpr, no argv
    # substring guessing) and before any test touches a device — jax
    # initializes backends lazily, so the env is set in time.
    if (config.option.markexpr or "").strip() == "not slow" and \
            "xla_backend_optimization_level" not in os.environ.get(
                "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] += " --xla_backend_optimization_level=0"


def pytest_sessionstart(session):
    global _t_session_start
    import time
    _t_session_start = time.time()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    # the tier wall time is a tracked number (VERDICT r4 weakness #5:
    # "quick" must stay quick) — print it where it can't be missed
    import time
    if _t_session_start is not None:
        wall = time.time() - _t_session_start
        tier = "quick" if "not slow" in (config.option.markexpr or "") \
            else "full"
        terminalreporter.write_line(
            f"[deepspeed_tpu] {tier}-tier wall time: {wall:.1f}s"
            + (" (target <180s)" if tier == "quick" else ""))


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


def make_batch(batch_size: int, seq_len: int, vocab: int = 256, seed: int = 0):
    r = np.random.default_rng(seed)
    return {"input_ids": r.integers(0, vocab, size=(batch_size, seq_len), dtype=np.int32)}


@pytest.fixture()
def tiny_model():
    from deepspeed_tpu.models import TransformerConfig, make_model
    import jax.numpy as jnp
    cfg = TransformerConfig(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
        max_seq_len=128, dtype=jnp.float32, attention_impl="xla")
    return make_model(cfg, name="tiny")
