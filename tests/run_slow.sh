#!/usr/bin/env bash
# Slow-tier certification with per-module wall budgets + incremental output.
#
# The monolithic `pytest -m slow tests/` run emits nothing until the end and
# can blow a judge/CI box's wall budget with zero signal (VERDICT Weak #8:
# killed at 50 min, no output). This driver runs the slow tier one module at
# a time, each under `timeout`, printing a pass/fail/time line as soon as the
# module finishes — so a partial run still certifies the modules it reached,
# and a hung module costs its budget, not the whole round.
#
# Usage:
#   tests/run_slow.sh                 # every module with slow-marked tests
#   tests/run_slow.sh infinity moe    # only modules matching these substrings
#   SLOW_BUDGET=900 tests/run_slow.sh # per-module wall budget (default 600s)
#   CHAOS_BUDGET=1200 tests/run_slow.sh chaos  # chaos-soak override: the
#       soak replays ~15 steps on top of 2x50 and rebuilds engines 4+ times,
#       so it carries its own budget independent of the default tier budget
#   SERVING_CHAOS_BUDGET=600 tests/run_slow.sh serving_chaos  # serving soak:
#       3 interpret-Pallas engine builds + a 40-round faulted load +
#       drain/resume (ISSUE 10)
#   ROUTER_CHAOS_BUDGET=600 tests/run_slow.sh router_chaos  # router soak:
#       2-replica load under replica kills / partitions / spill storms,
#       bit-identical to the fault-free single-replica run (ISSUE 11)
#   LATENCY_BUDGET=420 tests/run_slow.sh prefix_cache spec_decode  # the
#       latency-frontier parity runs: warm-vs-cold prefix cache and
#       spec-on-vs-off over full serving loads, bf16 + int8 (ISSUE 12)
#   OFFLOAD_BUDGET=600 tests/run_slow.sh offload_pipeline  # ISSUE 14:
#       pipelined-vs-drained bit-for-bit parity (3 engine pairs x 20 fp16
#       steps, NVMe + tmpfs), mid-step read-fault recovery, and the
#       offload-serial-pipeline audit twins (each builds a real executor
#       with injected storage latency)
#   TP_SERVING_BUDGET=420 tests/run_slow.sh tp_serving  # ISSUE 15:
#       tp=2-vs-single-chip serving parity under preemption + prefix
#       cache + the latency tier, and the tp2->tp2 drained continuation
#   LORA_BUDGET=420 tests/run_slow.sh lora_serving  # ISSUE 17: the
#       rotating-tenant churn soak (evict/re-page under all-pinned
#       preemptions, latency stack on) vs per-tenant merged-dense
#       serial engines, token-for-token
#   OBS_BUDGET=420 tests/run_slow.sh fleet_obs  # ISSUE 18: the fleet
#       rollup truth test (2 engine builds + a full routed load) and the
#       traced 2-replica kill/failover stitch, bit-compared against an
#       untraced fault-free run
#   FLEET_BUDGET=420 tests/run_slow.sh disagg  # ISSUE 19: the tp2->tp2
#       KV-byte handoff parity run and the engine-backed burst/lull
#       autoscale soak (FleetController scale events, zero lost)
#   PROTO_BUDGET=420 tests/run_slow.sh proto modelcheck  # ISSUE 20: the
#       exhaustive control-plane model-check soaks (full 8-event space
#       at the shipped depth + the fencing alphabet one ring deeper,
#       each sequence a fresh real-router world)
#
# Quick-tier tests are certified separately (pytest -m 'not slow'); this
# driver runs ONLY the slow-marked tests of each module (-m slow) so the two
# tiers compose to the full suite without double-running anything.

set -u
cd "$(dirname "$0")/.."

BUDGET="${SLOW_BUDGET:-600}"
PYTEST_ARGS=(-q -m slow -p no:cacheprovider -p no:xdist -p no:randomly
             --continue-on-collection-errors)

modules=()
for f in tests/unit/test_*.py tests/unit/ops/test_*.py; do
    # only modules that actually carry slow-marked tests
    grep -q "pytest.mark.slow" "$f" || continue
    if [ "$#" -gt 0 ]; then
        keep=0
        for pat in "$@"; do
            case "$f" in *"$pat"*) keep=1 ;; esac
        done
        [ "$keep" = 1 ] || continue
    fi
    modules+=("$f")
done

if [ "${#modules[@]}" -eq 0 ]; then
    echo "run_slow: no slow-marked modules matched" >&2
    exit 2
fi

total=0; failed=0; timedout=0
summary=""
t_all=$(date +%s)
for m in "${modules[@]}"; do
    total=$((total + 1))
    # per-module budget overrides (fault-injection soaks rebuild engines
    # repeatedly and own a budget independent of the tier default)
    budget="$BUDGET"
    case "$m" in
        *test_chaos*) budget="${CHAOS_BUDGET:-900}" ;;
        # real jax.profiler captures: 3 engine builds + a profiled fp16
        # parity run; the profiler start/stop and trace export are wall
        # time the other suites don't pay
        *test_trace_analysis*) budget="${TRACE_BUDGET:-420}" ;;
        # ISSUE-8 numerics parity: 4 parametrized cases x 2 engine builds
        # x 20 fp16 steps (fused attention backward + chunked TP overlap,
        # ZeRO 1/3) — interpret-mode Pallas makes the fused pair the cost
        *test_perf_levers*) budget="${PERF_LEVERS_BUDGET:-420}" ;;
        # ISSUE-12 latency frontier: engine-parity runs (warm-vs-cold
        # prefix cache, spec K>0 vs off, int8 variants) — each builds 2+
        # serving engines and decodes full loads, budgeted together
        *test_prefix_cache*|*test_spec_decode*)
            budget="${LATENCY_BUDGET:-420}" ;;
        # ISSUE-14 overlapped offload pipeline: bit-for-bit parity pairs
        # (2 engines x 20 fp16 steps each, NVMe + tmpfs + native host-Adam
        # variants) + the injected-latency audit twins
        *test_offload_pipeline*) budget="${OFFLOAD_BUDGET:-600}" ;;
        # ISSUE-11 router chaos soak: a 2-replica mixed load under
        # replica kills + heartbeat-loss partitions + saturation storms,
        # compared bit-for-bit against a fault-free single-replica run —
        # three engine builds + 30+ routing rounds (matched before the
        # *test_serving* glob, like SERVING_CHAOS_BUDGET)
        *test_router_chaos*) budget="${ROUTER_CHAOS_BUDGET:-600}" ;;
        # ISSUE-10 serving chaos soak: three engine builds on interpret-
        # mode Pallas + a 40-round faulted load + drain/resume — budgeted
        # separately from the quick serving module (matched FIRST: the
        # *test_serving* glob below would swallow it)
        *test_serving_chaos*) budget="${SERVING_CHAOS_BUDGET:-600}" ;;
        # ISSUE-15 pod-scale serving: tp=2-vs-single-chip parity pairs
        # (preemption + prefix cache, spec/chunked latency tier, drained
        # continuation) — each builds 2 engines per mesh and serves full
        # loads on the 2-device CPU mesh (matched before the
        # *test_serving* glob below)
        *test_tp_serving*) budget="${TP_SERVING_BUDGET:-420}" ;;
        # ISSUE-17 multi-tenancy: the rotating-tenant churn soak builds
        # one pooled engine + one merged-dense engine per tenant and
        # decodes full loads with the latency stack on (matched before
        # the *test_serving* glob below)
        *test_lora_serving*) budget="${LORA_BUDGET:-420}" ;;
        # ISSUE-18 fleet observability: the rollup-vs-truth and traced
        # kill/failover stitch tests each build 2-3 engines and serve
        # full routed loads (matched before the *test_serving* glob
        # below)
        *test_fleet_obs*) budget="${OBS_BUDGET:-420}" ;;
        # ISSUE-19 disaggregated serving: the tp2->tp2 handoff parity
        # run (3 sharded engine builds) and the burst/lull autoscale
        # soak over real engines with FleetController scale events
        *test_disagg*) budget="${FLEET_BUDGET:-420}" ;;
        # ISSUE-9 serving tier: multi-tenant end-to-end runs (engine
        # rebuilds + per-bucket prefill compiles + int8 pool parity over
        # 24 decode steps) own a budget independent of the tier default
        *test_serving*) budget="${SERVING_BUDGET:-420}" ;;
        # ISSUE-16 race-explorer soaks: exhaustive decision-tree sweeps
        # of the corpus harnesses + 1000-schedule random soaks of the
        # corrected twins + the full two-face CLI gate
        *test_race_lint*) budget="${RACE_BUDGET:-420}" ;;
        # ISSUE-20 protocol + model-check soaks: the full 8-event
        # control-plane space at the shipped depth and the fencing
        # alphabet one ring deeper — each sequence boots a real router
        # (and, for the full alphabet, a FleetController) in a fresh
        # world, so the soak is hundreds of router lifecycles
        *test_proto_lint*|*test_modelcheck*)
            budget="${PROTO_BUDGET:-420}" ;;
    esac
    t0=$(date +%s)
    out=$(timeout -k 10 "$budget" \
          env JAX_PLATFORMS=cpu python -m pytest "$m" "${PYTEST_ARGS[@]}" 2>&1)
    rc=$?
    dt=$(( $(date +%s) - t0 ))
    tail_line=$(printf '%s\n' "$out" | grep -aE "passed|failed|error|no tests ran" | tail -1)
    if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
        status="TIMEOUT(${budget}s)"
        timedout=$((timedout + 1))
    elif [ "$rc" -eq 5 ] || printf '%s' "$tail_line" | grep -q "no tests ran"; then
        status="no-slow-tests"   # marker only in skipped/parametrized paths
    elif [ "$rc" -ne 0 ]; then
        status="FAIL(rc=$rc)"
        failed=$((failed + 1))
        printf '%s\n' "$out" | tail -30
    else
        status="ok"
    fi
    line=$(printf '%-46s %-14s %4ss  %s' "$m" "$status" "$dt" "${tail_line:-}")
    echo "$line"
    summary+="$line"$'\n'
done

echo "----------------------------------------------------------------------"
echo "run_slow: ${total} module(s), ${failed} failed, ${timedout} timed out," \
     "$(( $(date +%s) - t_all ))s total (budget ${BUDGET}s/module)"
[ "$failed" -eq 0 ] && [ "$timedout" -eq 0 ]
